"""Query tree -> (plan, bindings) against a shard's mapping + collection
statistics.

Analog of ``QueryBuilder.toQuery(QueryShardContext)``
(ref index/query/QueryShardContext.java:95) plus the Lucene Weight
construction it triggers: idf/avgdl are computed here from CROSS-SEGMENT
stats (Lucene computes them in IndexSearcher.termStatistics over the whole
reader, not per leaf), so scores are consistent across segments.
"""

from __future__ import annotations

import ipaddress
import math
import re
from dataclasses import dataclass

import numpy as np

from opensearch_tpu.common.errors import (IllegalArgumentError,
                                          OpenSearchTpuError, ParsingError)
from opensearch_tpu.mapping.types import (
    DenseVectorFieldType,
    KeywordFieldType,
    TextFieldType,
    parse_date_millis,
    parse_ip_long,
)
from opensearch_tpu.ops import bm25 as bm25_ops
from opensearch_tpu.search import query_dsl as dsl
from opensearch_tpu.search import plan as P

_I64_MIN = -(2**63)
_I64_MAX = 2**63 - 1


@dataclass
class FieldStats:
    doc_count: int
    total_len: float

    @property
    def avgdl(self) -> float:
        return self.total_len / self.doc_count if self.doc_count else 1.0


class ShardContext:
    """Per-searcher compile context: mapping + collection statistics over
    the segment set (IndexSearcher.collectionStatistics analog)."""

    def __init__(self, segments, mapper):
        self.segments = segments
        self.mapper = mapper
        # point-in-time live-bitmap snapshot (apply_deletes replaces the
        # array, so this context keeps seeing the state at acquire time)
        self.lives = {id(s): s.live for s in segments}
        self._fstats: dict[str, FieldStats] = {}
        self._sorted_terms: dict[tuple[int, str], list[str]] = {}

    def live_jnp(self, seg, dseg):
        return dseg.live_jnp(self.lives[id(seg)])

    def field_type(self, field: str):
        return self.mapper.field_type(field)

    def field_stats(self, field: str) -> FieldStats:
        st = self._fstats.get(field)
        if st is None:
            doc_count = 0
            total_len = 0.0
            for seg in self.segments:
                pf = seg.postings.get(field)
                if pf is not None:
                    doc_count += pf.docs_with_field
                    total_len += pf.total_len
            st = FieldStats(doc_count, total_len)
            self._fstats[field] = st
        return st

    def df(self, field: str, term: str) -> int:
        total = 0
        for seg in self.segments:
            pf = seg.postings.get(field)
            if pf is not None:
                tid = pf.term_id(term)
                if tid >= 0:
                    total += int(pf.df[tid])
        return total

    def sorted_terms(self, seg, field: str) -> list[str]:
        key = (id(seg), field)
        out = self._sorted_terms.get(key)
        if out is None:
            out = list(seg.postings[field].terms)
            self._sorted_terms[key] = out
        return out

    def text_fields(self) -> list[str]:
        return [f for f, ft in self.mapper.field_types().items()
                if isinstance(ft, TextFieldType)]


def calc_min_should_match(optional: int, spec) -> int:
    """Lucene ``Queries.calculateMinShouldMatch`` subset: int, "-int",
    "N%", "-N%" (conditional "N<P" specs unsupported).  Percentages
    truncate toward zero (Java int cast).  May return a value LARGER than
    ``optional`` — the caller must then match nothing (Lucene rewrites to
    MatchNoDocsQuery)."""
    if spec is None:
        return 0
    s = str(spec).strip()
    if "<" in s:
        raise IllegalArgumentError(
            f"conditional minimum_should_match [{s}] is not supported")
    if s.endswith("%"):
        pct = int(s[:-1])
        result = (optional + int(optional * pct / 100.0) if pct < 0
                  else int(optional * pct / 100.0))
    else:
        n = int(s)
        result = n if n >= 0 else optional + n
    return max(0, result)


def _idfs_for(ctx: ShardContext, field: str, terms: list[str]) -> np.ndarray:
    stats = ctx.field_stats(field)
    return np.asarray(
        [bm25_ops.idf(ctx.df(field, t), stats.doc_count) for t in terms],
        dtype=np.float32)


def _term_bag(ctx, field, terms, required, boost, scored):
    idfs = _idfs_for(ctx, field, terms)
    bind = {"terms": tuple(terms), "idfs": idfs,
            "weights": np.full(len(terms), boost, np.float32),
            "avgdl": ctx.field_stats(field).avgdl, "required": required}
    return P.TermBagPlan(field=field, scored=scored), bind


def _none():
    return P.MatchNonePlan(), {}


def _require_ft(ctx, field, qname):
    ft = ctx.field_type(field)
    if ft is None:
        return None
    if not ft.index_enabled and ft.dv_kind == "none":
        raise IllegalArgumentError(
            f"Cannot search on field [{field}] since it is not indexed")
    return ft


def _ip_cidr_bind(value: str, boost: float) -> dict:
    net = ipaddress.ip_network(str(value), strict=False)
    return {"lo": parse_ip_long(net.network_address),
            "hi": parse_ip_long(net.broadcast_address), "boost": boost}


def compile_query(q: dsl.Query, ctx: ShardContext, scored: bool = True,
                  prof=None):
    """Returns (plan, bind).  ``prof`` (a QueryProfiler) times the plan
    construction into the ``compile`` phase and records the root plan
    type — the compiler is a profile feeder, never a consumer."""
    fn = _COMPILERS.get(type(q))
    if fn is None:
        raise IllegalArgumentError(
            f"query type [{type(q).__name__}] is not supported")
    if prof is None:
        return fn(q, ctx, scored)
    import time
    t0 = time.monotonic()
    out = fn(q, ctx, scored)
    prof.add("compile", time.monotonic() - t0)
    prof.set("query_type", type(q).__name__)
    return out


def _c_match_all(q, ctx, scored):
    return P.MatchAllPlan(), {"boost": q.boost}


def _c_match_none(q, ctx, scored):
    return _none()


def _c_term(q, ctx, scored):
    if q.field == "_id":
        # term/terms on the _id metafield = an ids query
        # (IdFieldMapper.termQuery)
        return _c_ids(dsl.IdsQuery(values=[str(q.value)], boost=q.boost),
                      ctx, scored)
    ft = _require_ft(ctx, q.field, "term")
    if ft is None:
        return _none()
    if ft.type_name == "ip":
        if "/" in str(q.value):
            return (P.NumericRangePlan(field=q.field, kind="long"),
                    _ip_cidr_bind(q.value, q.boost))
        term = str(ipaddress.ip_address(str(q.value)))
        return _term_bag(ctx, q.field, [term], 1, q.boost, scored)
    if ft.dv_kind in ("long", "double") and ft.type_name != "boolean":
        return (P.NumericTermsPlan(field=q.field, kind=ft.dv_kind),
                {"values": [ft.term_for_query(q.value)], "boost": q.boost})
    term = ft.term_for_query(q.value)
    return _term_bag(ctx, q.field, [term], 1, q.boost, scored)


def _c_terms(q, ctx, scored):
    if q.field == "_id":
        return _c_ids(dsl.IdsQuery(values=[str(v) for v in q.values],
                                   boost=q.boost), ctx, scored)
    ft = _require_ft(ctx, q.field, "terms")
    if ft is None or not q.values:
        return _none()
    if ft.type_name == "ip":
        cidrs = [v for v in q.values if "/" in str(v)]
        exact = [str(ipaddress.ip_address(str(v))) for v in q.values
                 if "/" not in str(v)]
        if cidrs:
            children, binds = [], []
            if exact:
                p = P.PostingsMaskPlan(field=q.field)
                children.append(p)
                binds.append({"terms": tuple(exact), "boost": 1.0})
            for c in cidrs:
                net = ipaddress.ip_network(str(c), strict=False)
                children.append(P.NumericRangePlan(field=q.field, kind="long"))
                binds.append({"lo": parse_ip_long(net.network_address),
                              "hi": parse_ip_long(net.broadcast_address),
                              "boost": 1.0})
            inner = P.BoolPlan(should=tuple(children))
            return (P.ConstScorePlan(child=inner),
                    {"boost": q.boost,
                     "child": {"boost": 1.0, "required": 1,
                               "children": tuple(binds)}})
        return (P.PostingsMaskPlan(field=q.field),
                {"terms": tuple(exact), "boost": q.boost})
    if ft.dv_kind in ("long", "double") and ft.type_name != "boolean":
        return (P.NumericTermsPlan(field=q.field, kind=ft.dv_kind),
                {"values": [ft.term_for_query(v) for v in q.values],
                 "boost": q.boost})
    terms = [ft.term_for_query(v) for v in q.values]
    return (P.PostingsMaskPlan(field=q.field),
            {"terms": tuple(terms), "boost": q.boost})


def _c_match(q, ctx, scored):
    ft = _require_ft(ctx, q.field, "match")
    if ft is None:
        return _none()
    if not isinstance(ft, TextFieldType):
        try:
            return _c_term(dsl.TermQuery(field=q.field, value=q.query,
                                         boost=q.boost), ctx, scored)
        except (OpenSearchTpuError, ValueError):
            if q.lenient:
                return _none()
            raise
    qa = getattr(q, "analyzer", None)
    if qa:
        terms = ctx.mapper.analyzers.get(qa).terms(str(q.query))
    else:
        terms = ft.search_terms(q.query, ctx.mapper.analyzers)
    if not terms:
        return _none()
    if q.fuzziness is not None:
        children, binds = [], []
        for t in terms:
            children.append(P.ExpandTermsPlan(field=q.field, mode="fuzzy"))
            binds.append({"pattern": t, "fuzzy_dist": _auto_fuzzy(q.fuzziness, t),
                          "prefix_length": 0, "boost": q.boost})
        required = (len(terms) if q.operator == "and"
                    else max(1, calc_min_should_match(
                        len(terms), q.minimum_should_match)))
        # fuzzy clauses are constant-score masks; combine as bool
        plan = P.BoolPlan(should=tuple(children))
        return plan, {"boost": 1.0, "required": required,
                      "children": tuple(binds)}
    if q.operator == "and":
        required = len(terms)
    else:
        required = max(1, calc_min_should_match(len(terms),
                                                q.minimum_should_match))
    if required > len(terms):
        return _none()
    return _term_bag(ctx, q.field, terms, required, q.boost, scored)


def _auto_fuzzy(fuzziness, term: str) -> int:
    s = str(fuzziness).upper()
    if s.startswith("AUTO"):
        n = len(term)
        return 0 if n < 3 else (1 if n <= 5 else 2)
    return int(float(s))


def _c_match_phrase(q, ctx, scored):
    ft = _require_ft(ctx, q.field, "match_phrase")
    if ft is None:
        return _none()
    if not isinstance(ft, TextFieldType):
        return _c_term(dsl.TermQuery(field=q.field, value=q.query,
                                     boost=q.boost), ctx, scored)
    analyzer = ctx.mapper.analyzers.get(ft.search_analyzer_name)
    toks = analyzer.analyze(str(q.query))
    if not toks:
        return _none()
    if len(toks) == 1:
        return _term_bag(ctx, q.field, [toks[0].term], 1, q.boost, scored)
    if q.slop:
        raise IllegalArgumentError("match_phrase slop > 0 is not supported yet")
    terms = [t.term for t in toks]
    positions = [t.position for t in toks]
    stats = ctx.field_stats(q.field)
    idf_sum = float(np.sum(_idfs_for(ctx, q.field, terms)))
    bind = {"terms": tuple(terms), "positions": tuple(positions),
            "idf_sum": idf_sum, "boost": q.boost, "avgdl": stats.avgdl}
    return P.PhrasePlan(field=q.field, scored=scored), bind


def _c_multi_match(q, ctx, scored):
    if q.type == "bool_prefix":
        # dis-max of per-field match_bool_prefix
        # (MultiMatchQueryBuilder.Type.BOOL_PREFIX)
        plans, binds = [], []
        for field, fboost in q.fields:
            if ctx.field_type(field) is None:
                continue
            p, b = _c_match_bool_prefix(dsl.MatchBoolPrefixQuery(
                field=field, query=q.query, operator=q.operator,
                analyzer=getattr(q, "analyzer", None),
                minimum_should_match=q.minimum_should_match,
                fuzziness=getattr(q, "fuzziness", None),
                boost=q.boost * fboost), ctx, scored)
            if not isinstance(p, P.MatchNonePlan):
                plans.append(p)
                binds.append(b)
        if not plans:
            return _none()
        if len(plans) == 1:
            return plans[0], binds[0]
        return (P.DisMaxPlan(children=tuple(plans)),
                {"boost": 1.0, "tie_breaker": q.tie_breaker,
                 "children": tuple(binds)})
    if q.type not in ("best_fields", "most_fields", "phrase"):
        raise IllegalArgumentError(
            f"multi_match type [{q.type}] is not supported")
    # "*" expands to every text field (the lenient all-fields default the
    # simple_query_string path already has)
    fields = []
    for field, fboost in q.fields:
        if field == "*":
            fields.extend((f, fboost) for f in ctx.text_fields())
        else:
            fields.append((field, fboost))
    children, binds = [], []
    for field, fboost in fields:
        if ctx.field_type(field) is None:
            continue
        if q.type == "phrase":
            sub = dsl.MatchPhraseQuery(field=field, query=q.query,
                                       boost=q.boost * fboost)
            p, b = _c_match_phrase(sub, ctx, scored)
        else:
            sub = dsl.MatchQuery(field=field, query=q.query,
                                 operator=q.operator,
                                 minimum_should_match=q.minimum_should_match,
                                 lenient=getattr(q, "lenient", False),
                                 analyzer=getattr(q, "analyzer", None),
                                 boost=q.boost * fboost)
            p, b = _c_match(sub, ctx, scored)
        if not isinstance(p, P.MatchNonePlan):
            children.append(p)
            binds.append(b)
    if not children:
        return _none()
    if len(children) == 1:
        return children[0], binds[0]
    plan = P.DisMaxPlan(children=tuple(children))
    return plan, {"boost": 1.0, "tie_breaker": q.tie_breaker,
                  "children": tuple(binds)}


def _c_bool(q, ctx, scored):
    groups = {}
    for name, qs, sub_scored in (("must", q.must, scored),
                                 ("should", q.should, scored),
                                 ("must_not", q.must_not, False),
                                 ("filter", q.filter, False)):
        plans, binds = [], []
        for sub in qs:
            p, b = compile_query(sub, ctx, sub_scored)
            plans.append(p)
            binds.append(b)
        groups[name] = (tuple(plans), tuple(binds))
    n_should = len(groups["should"][0])
    if q.minimum_should_match is not None:
        required = calc_min_should_match(n_should, q.minimum_should_match)
        if required > n_should:
            return _none()   # Lucene rewrites to MatchNoDocsQuery
    else:
        required = 0 if (q.must or q.filter) else (1 if n_should else 0)
    plan = P.BoolPlan(must=groups["must"][0], should=groups["should"][0],
                      must_not=groups["must_not"][0],
                      filter=groups["filter"][0])
    bind = {"boost": q.boost, "required": required,
            "children": (groups["must"][1] + groups["should"][1]
                         + groups["must_not"][1] + groups["filter"][1])}
    return plan, bind


def _c_range(q, ctx, scored):
    if getattr(q, "lenient", False):
        try:
            return _c_range_strict(q, ctx, scored)
        except (OpenSearchTpuError, ValueError):
            return _none()
    return _c_range_strict(q, ctx, scored)


def _c_range_strict(q, ctx, scored):
    ft = _require_ft(ctx, q.field, "range")
    if ft is None:
        return _none()
    if isinstance(ft, TextFieldType):
        raise IllegalArgumentError(
            f"range query on [text] field [{q.field}] is not supported")
    if isinstance(ft, KeywordFieldType):
        lo, lo_incl = (q.gte, True) if q.gte is not None else (q.gt, False)
        hi, hi_incl = (q.lte, True) if q.lte is not None else (q.lt, False)
        bind = {"lo": None if lo is None else str(lo), "lo_incl": lo_incl,
                "hi": None if hi is None else str(hi), "hi_incl": hi_incl,
                "boost": q.boost}
        return P.OrdinalRangePlan(field=q.field), bind
    kind = "double" if ft.dv_kind == "double" else "long"
    if kind == "long":
        lo = _I64_MIN if q.gte is None and q.gt is None else (
            ft.range_bound(q.gte) if q.gte is not None
            else ft.range_bound(q.gt) + 1)
        hi = _I64_MAX if q.lte is None and q.lt is None else (
            ft.range_bound(q.lte) if q.lte is not None
            else ft.range_bound(q.lt) - 1)
        return (P.NumericRangePlan(field=q.field, kind="long"),
                {"lo": lo, "hi": hi, "boost": q.boost})
    lo, lo_incl = (-np.inf, True)
    if q.gte is not None:
        lo, lo_incl = float(ft.range_bound(q.gte)), True
    elif q.gt is not None:
        lo, lo_incl = float(ft.range_bound(q.gt)), False
    hi, hi_incl = (np.inf, True)
    if q.lte is not None:
        hi, hi_incl = float(ft.range_bound(q.lte)), True
    elif q.lt is not None:
        hi, hi_incl = float(ft.range_bound(q.lt)), False
    return (P.NumericRangePlan(field=q.field, kind="double",
                               include_lo=lo_incl, include_hi=hi_incl),
            {"lo": lo, "hi": hi, "boost": q.boost})


def _c_exists(q, ctx, scored):
    if q.field in ("_id", "_index", "_seq_no", "_version"):
        # always-present metafields: every live doc matches
        # (exists rewrites to match_all for fields with norms/dv on all
        # docs — MetadataFieldMapper existence semantics)
        return _c_match_all(dsl.MatchAllQuery(boost=q.boost), ctx, scored)
    ft = ctx.field_type(q.field)
    if ft is None or ft.type_name == "object":
        # object container (explicit or implicit): exists = any child
        # field exists (ObjectMapper existence expansion)
        children = [f for f in getattr(ctx.mapper, "_fields", {})
                    if f.startswith(q.field + ".")]
        if not children:
            return _none()
        return _c_bool(dsl.BoolQuery(
            should=[dsl.ExistsQuery(field=f) for f in children],
            boost=q.boost), ctx, scored)
    src = {"long": "numeric", "double": "numeric", "ordinal": "ordinal",
           "vector": "vector", "geo_point": "geo", "none": "norms"}[ft.dv_kind]
    if src != "norms" and not ft.doc_values_enabled:
        if ft.indexed and ft.index_enabled:
            # doc_values disabled but indexed: existence via the
            # postings presence column (the reference's _field_names
            # fallback)
            src = "norms"
        else:
            raise IllegalArgumentError(
                f"exists on field [{q.field}] requires doc_values or an "
                "indexed field")
    return P.ExistsPlan(field=q.field, src=src), {"boost": q.boost}


# -- parent-join (modules/parent-join) --------------------------------------


def _find_join_field(ctx):
    for f, ft in ctx.mapper.field_types().items():
        if ft.type_name == "join":
            return f, ft
    return None, None


def _host_run_scored(ctx, q):
    """Run an inner query over every segment host-side; [(seg, scores
    np[n_pad], matched np[n_pad])].  The pre-pass the join queries (and
    knn before them) inject via ScoredMaskPlan."""
    from opensearch_tpu.search.executor import build_arrays
    from opensearch_tpu.search.plan import run_full

    import jax.numpy as jnp

    plan, bind = compile_query(q, ctx, scored=True)
    needed = plan.arrays()
    neg_inf = jnp.asarray(np.float32(-np.inf))  # staging-ok: per-query input
    out = []
    for seg in ctx.segments:
        dseg = seg.device()
        A = build_arrays(dseg, needed, ctx.mapper,
                         live=ctx.live_jnp(seg, dseg))
        dims, ins = plan.prepare(bind, seg, dseg, ctx)
        scores, matched = run_full(plan, dims, A, ins, neg_inf)
        out.append((seg, np.asarray(scores), np.asarray(matched)))
    return out


def _ord_per_doc(seg, field) -> dict:
    """doc -> term for a single-valued hidden ordinal column, cached on
    the segment (segments are immutable)."""
    from opensearch_tpu.common.cache import attached_cache
    cache = attached_cache(seg, "_join_col_cache",
                           name="query.join_columns",
                           max_weight=32 << 20, breaker="fielddata")
    out = cache.get(field)
    if out is None:
        dv = seg.ordinal_dv.get(field)
        out = {} if dv is None else {
            int(d): dv.ord_terms[o]
            for d, o in zip(dv.value_docs, dv.ords) if o >= 0}
        cache.put(field, out)
    return out


def _join_mask_plan(ctx, fn, label):
    return P.ScoredMaskPlan(label=label), {"fn": fn}


def _c_has_child(q, ctx, scored):
    field, jft = _find_join_field(ctx)
    if field is None:
        return _none()
    parent_rel = jft.parent_of(q.type)
    if parent_rel is None:
        raise IllegalArgumentError(
            f"[has_child] join field [{field}] has no child relation "
            f"[{q.type}]")
    state: dict = {}

    def compute():
        agg: dict = {}      # parent _id -> [count, total, mx, mn]
        for seg, scores, matched in _host_run_scored(ctx, q.query):
            names = _ord_per_doc(seg, field + "#name")
            parents = _ord_per_doc(seg, field + "#parent")
            for local in np.nonzero(matched[: seg.n_docs])[0]:
                local = int(local)
                if names.get(local) != q.type:
                    continue
                pid = parents.get(local)
                if pid is None:
                    continue
                s = float(scores[local])
                cur = agg.get(pid)
                if cur is None:
                    agg[pid] = [1, s, s, s]
                else:
                    cur[0] += 1
                    cur[1] += s
                    cur[2] = max(cur[2], s)
                    cur[3] = min(cur[3], s)
        out = {}
        for pid, (count, total, mx, mn) in agg.items():
            if count < q.min_children:
                continue
            if q.max_children is not None and count > q.max_children:
                continue
            out[pid] = {"none": 1.0, "sum": total, "max": mx, "min": mn,
                        "avg": total / count}.get(q.score_mode, 1.0)
        state["scores"] = out

    def fn(seg, dseg):
        if "scores" not in state:
            compute()
        sc = np.zeros(dseg.n_pad, np.float32)
        mk = np.zeros(dseg.n_pad, bool)
        names = _ord_per_doc(seg, field + "#name")
        for pid, s in state["scores"].items():
            local = seg.id_to_local.get(pid)
            if local is None or not seg.live[local]:
                continue
            if names.get(local) != parent_rel:
                continue
            mk[local] = True
            sc[local] = q.boost * s
        return sc, mk

    return _join_mask_plan(ctx, fn, "has_child")


def _c_has_parent(q, ctx, scored):
    field, jft = _find_join_field(ctx)
    if field is None:
        return _none()
    if q.parent_type not in jft.relations:
        raise IllegalArgumentError(
            f"[has_parent] join field [{field}] has no parent relation "
            f"[{q.parent_type}]")
    state: dict = {}

    def compute():
        out = {}
        for seg, scores, matched in _host_run_scored(ctx, q.query):
            names = _ord_per_doc(seg, field + "#name")
            for local in np.nonzero(matched[: seg.n_docs])[0]:
                local = int(local)
                if names.get(local) != q.parent_type:
                    continue
                out[seg.doc_ids[local]] = float(scores[local])
        state["scores"] = out

    def fn(seg, dseg):
        if "scores" not in state:
            compute()
        sc = np.zeros(dseg.n_pad, np.float32)
        mk = np.zeros(dseg.n_pad, bool)
        parents = _ord_per_doc(seg, field + "#parent")
        for local, pid in parents.items():
            s = state["scores"].get(pid)
            if s is None or not seg.live[local]:
                continue
            mk[local] = True
            sc[local] = q.boost * (s if q.score else 1.0)
        return sc, mk

    return _join_mask_plan(ctx, fn, "has_parent")


def _c_parent_id(q, ctx, scored):
    field, jft = _find_join_field(ctx)
    if field is None:
        return _none()
    if jft.parent_of(q.type) is None:
        raise IllegalArgumentError(
            f"[parent_id] join field [{field}] has no child relation "
            f"[{q.type}]")

    def fn(seg, dseg):
        sc = np.zeros(dseg.n_pad, np.float32)
        mk = np.zeros(dseg.n_pad, bool)
        names = _ord_per_doc(seg, field + "#name")
        parents = _ord_per_doc(seg, field + "#parent")
        for local, pid in parents.items():
            if pid == q.id and names.get(local) == q.type \
                    and seg.live[local]:
                mk[local] = True
                sc[local] = q.boost
        return sc, mk

    return _join_mask_plan(ctx, fn, "parent_id")


def _c_ids(q, ctx, scored):
    wanted = set(map(str, q.values))

    def mask_fn(seg, dseg):
        m = np.zeros(dseg.n_pad, bool)
        for did in wanted:
            loc = seg.id_to_local.get(did)
            if loc is not None:
                m[loc] = True
        return m

    return P.MaskPlan(label="ids"), {"mask_fn": mask_fn, "boost": q.boost}


_MAX_CODEPOINT = chr(0x10FFFF)


def _c_prefix(q, ctx, scored):
    ft = _require_ft(ctx, q.field, "prefix")
    if ft is None:
        return _none()
    value = str(q.value)
    return (P.TermRangeMaskPlan(field=q.field),
            {"lo": value, "hi": value + _MAX_CODEPOINT, "boost": q.boost})


def _c_wildcard(q, ctx, scored):
    ft = _require_ft(ctx, q.field, "wildcard")
    if ft is None:
        return _none()
    return (P.ExpandTermsPlan(field=q.field, mode="wildcard"),
            {"pattern": str(q.value), "fuzzy_dist": 0, "prefix_length": 0,
             "nocase": bool(getattr(q, "case_insensitive", False)),
             "boost": q.boost})


def _c_regexp(q, ctx, scored):
    ft = _require_ft(ctx, q.field, "regexp")
    if ft is None:
        return _none()
    return (P.ExpandTermsPlan(field=q.field, mode="regexp"),
            {"pattern": str(q.value), "fuzzy_dist": 0, "prefix_length": 0,
             "boost": q.boost})


def _c_fuzzy(q, ctx, scored):
    ft = _require_ft(ctx, q.field, "fuzzy")
    if ft is None:
        return _none()
    return (P.ExpandTermsPlan(field=q.field, mode="fuzzy"),
            {"pattern": str(q.value),
             "fuzzy_dist": _auto_fuzzy(q.fuzziness, str(q.value)),
             "prefix_length": q.prefix_length, "boost": q.boost})


def _c_constant_score(q, ctx, scored):
    child_plan, child_bind = compile_query(q.query, ctx, scored=False)
    return (P.ConstScorePlan(child=child_plan),
            {"boost": q.boost, "child": child_bind})


def _c_dis_max(q, ctx, scored):
    plans, binds = [], []
    for sub in q.queries:
        p, b = compile_query(sub, ctx, scored)
        plans.append(p)
        binds.append(b)
    if not plans:
        return _none()
    return (P.DisMaxPlan(children=tuple(plans)),
            {"boost": q.boost, "tie_breaker": q.tie_breaker,
             "children": tuple(binds)})


_SQS_TOKEN = re.compile(r'([+-]?)"([^"]*)"|([+-]?)(\S+)')


def _c_simple_query_string(q, ctx, scored):
    fields = q.fields
    if not fields or fields == [("*", 1.0)]:
        fields = [(f, 1.0) for f in ctx.text_fields()]
    sub_queries = []
    for m in _SQS_TOKEN.finditer(q.query.strip()):
        if m.group(2) is not None:       # quoted -> phrase operator
            sign, text, is_phrase = m.group(1), m.group(2), True
        else:
            sign, text, is_phrase = m.group(3), m.group(4), False
            text = text.lstrip("+-")
        if not text.strip():
            continue
        mm = dsl.MultiMatchQuery(fields=fields, query=text,
                                 type="phrase" if is_phrase else "best_fields")
        sub_queries.append((sign == "-", mm))
    if not sub_queries:
        return P.MatchAllPlan(), {"boost": q.boost}
    must, must_not, should = [], [], []
    for negate, mm in sub_queries:
        if negate:
            must_not.append(mm)
        elif q.default_operator == "and":
            must.append(mm)
        else:
            should.append(mm)
    return _c_bool(dsl.BoolQuery(must=must, must_not=must_not, should=should,
                                 boost=q.boost), ctx, scored)


def _c_knn(q, ctx, scored):
    """knn query: per-segment vector search — exact brute force (matmul +
    top-k, ops/knn.py) or ANN when the field mapping declares a ``method``
    of ``ivf``/``ivf_pq`` (cluster-probed search, ops/ivf.py; trained
    structure cached per immutable segment) — with the global per-shard k
    winners injected into the plan tree as a ScoredMaskPlan.  Optional
    ``filter`` restricts candidates BEFORE the k cut (the plugin's
    filtered-knn semantics; ANN falls back to exact under a filter, like
    the plugin's filtered exact-search rescue).  All segment programs are
    dispatched asynchronously; the host syncs ONCE per query.
    """
    import jax.numpy as jnp

    from opensearch_tpu.ops.ivf import IvfPqIndex, ivf_search, ivfpq_search_l2
    from opensearch_tpu.ops.knn import knn_topk_auto

    ft = ctx.field_type(q.field)
    if ft is None:
        return _none()
    if ft.dv_kind != "vector":
        raise IllegalArgumentError(
            f"[knn] query requires a knn_vector/dense_vector field, "
            f"[{q.field}] is [{ft.type_name}]")
    qvec = np.asarray(q.vector, np.float32)
    if qvec.shape != (ft.dims,):
        raise IllegalArgumentError(
            f"query vector has dimension {qvec.shape[0]} but field "
            f"[{q.field}] expects {ft.dims}")
    space = {"l2": "l2", "cosinesimil": "cosinesimil",
             "innerproduct": "innerproduct"}.get(ft.space_type, "l2")

    method = dict(getattr(ft, "method", None) or {})
    # method_parameters is a SEARCH-TIME knob: only nprobe may be
    # overridden per request — structural params (name/nlist/m) define
    # the trained structure and honoring them here would retrain k-means
    # on the query path per distinct value
    if q.method_parameters and "nprobe" in q.method_parameters:
        method["nprobe"] = int(q.method_parameters["nprobe"])
    ann_name = method.get("name")
    use_ann = ann_name in ("ivf", "ivf_pq")

    filter_state = None
    if q.filter is not None:
        filter_state = compile_query(q.filter, ctx, scored=False)

    qvec_j = jnp.asarray(qvec)  # staging-ok: per-query input
    # phase 1: dispatch every segment's device program, keep DEVICE arrays
    pending = []             # (seg_order, vals_dev, idx_dev)
    for seg_order, seg in enumerate(ctx.segments):
        dseg = seg.device()
        vcol = dseg.vector.get(q.field)
        if vcol is None:
            continue
        live = ctx.live_jnp(seg, dseg)
        valid = vcol["exists"] & live
        if filter_state is not None:
            from opensearch_tpu.search.executor import build_arrays
            fplan, fbind = filter_state
            A = build_arrays(dseg, fplan.arrays(), ctx.mapper)
            dims, ins = fplan.prepare(fbind, seg, dseg, ctx)
            _s, fmask = P.run_full(fplan, dims, A, ins,
                                   jnp.asarray(np.float32(-np.inf)))  # staging-ok: per-query input
            valid = valid & fmask
        kk = min(q.k, dseg.n_pad)
        ann = (seg.ann_index(q.field, method)
               if use_ann and filter_state is None else None)
        if ann is not None:
            nprobe = min(int(method.get("nprobe", 0))
                         or max(1, ann.nlist // 8), ann.nlist)
            # the probed candidate pool is nprobe*c_pad rows — top_k past
            # that is a compile error
            kk = min(kk, nprobe * ann.c_pad)
            staged = dseg.ann_staged(ann)
            if isinstance(ann, IvfPqIndex) and space == "l2":
                vals, idx = ivfpq_search_l2(*staged, qvec_j, valid,
                                            k=kk, nprobe=nprobe)
            else:
                # IvfIndex, or IVF-PQ in a non-l2 space (ADC tables are
                # l2-residual based; probe the flat layout instead)
                if isinstance(ann, IvfPqIndex):
                    ann = seg.ann_index(q.field, {**method, "name": "ivf"})
                    staged = dseg.ann_staged(ann)
                vals, idx = ivf_search(*staged, qvec_j, valid,
                                       space=space, k=kk, nprobe=nprobe)
        else:
            vals, idx = knn_topk_auto(vcol["values"], valid, qvec_j,
                                      space=space, k=kk)
        pending.append((seg_order, vals, idx))
    # phase 2: one host sync for all segments' top-k
    candidates = []          # (score, seg_order, local)
    for seg_order, vals, idx in pending:
        vals, idx = np.asarray(vals), np.asarray(idx)
        keep = (vals > -np.inf) & (idx >= 0)
        for v, i in zip(vals[keep], idx[keep]):
            candidates.append((float(v), seg_order, int(i)))
    candidates.sort(key=lambda t: (-t[0], t[1], t[2]))
    winners: dict[int, list[tuple[int, float]]] = {}
    for score, seg_order, local in candidates[: q.k]:
        winners.setdefault(seg_order, []).append((local, score * q.boost))
    return _winners_plan(ctx, winners, "knn")


def _winners_plan(ctx, winners: dict, label: str):
    """(ScoredMaskPlan, bind) injecting host-computed per-segment winners
    {seg_order: [(local, score)]} into the plan tree (shared by the knn
    pre-pass and percolate)."""
    seg_order_by_id = {id(s): i for i, s in enumerate(ctx.segments)}

    def fn(seg, dseg):
        scores = np.zeros(dseg.n_pad, np.float32)
        mask = np.zeros(dseg.n_pad, bool)
        for local, score in winners.get(
                seg_order_by_id.get(id(seg), -1), []):
            scores[local] = score
            mask[local] = True
        return scores, mask

    return P.ScoredMaskPlan(label=label), {"fn": fn}


def _c_percolate(q, ctx, scored):
    """percolate: reverse search (modules/percolator).  Each stored query
    (the ``percolator`` field's _source JSON) compiles and runs against a
    tiny in-memory segment holding the candidate document(s); stored
    queries that match ANY candidate become hits.  Matching happens at
    compile time — the result is a ScoredMaskPlan over the query docs
    (the same injection pattern as knn's pre-pass)."""
    from opensearch_tpu.index.segment import SegmentWriter
    from opensearch_tpu.search.executor import ShardSearcher
    from opensearch_tpu.search.query_dsl import parse_query

    ft = ctx.field_type(q.field)
    if ft is None or ft.type_name != "percolator":
        raise IllegalArgumentError(
            f"[percolate] field [{q.field}] must be a percolator field")
    # candidate docs in a throwaway searcher over an ISOLATED mapper
    # clone (the percolator's MemoryIndex analog) — dynamic resolution
    # of unmapped candidate fields must never leak into the live index
    # mapping
    from opensearch_tpu.mapping.mapper import DocumentMapper

    tmp_mapper = DocumentMapper(ctx.mapper.to_mapping())
    writer = SegmentWriter()
    parsed = [tmp_mapper.parse(f"_tmp_{i}", d)
              for i, d in enumerate(q.documents)]
    cand = ShardSearcher([writer.build(parsed, "_percolate_tmp")],
                         tmp_mapper)
    winners: dict[int, list[tuple[int, float]]] = {}
    for seg_order, seg in enumerate(ctx.segments):
        live = ctx.lives[id(seg)]    # the searcher's PIT snapshot
        for local in range(seg.n_docs):
            if not live[local]:
                continue
            stored = seg.source(local).get(q.field)
            if not isinstance(stored, dict):
                continue             # absent or malformed: never matches
            try:
                n = cand.count(stored)
            except OpenSearchTpuError:
                continue             # query shape our engine can't run
            if n > 0:
                winners.setdefault(seg_order, []).append(
                    (local, q.boost))
    return _winners_plan(ctx, winners, "percolate")


def _c_nested(q, ctx, scored):
    """nested query: inner conditions compile into object-space
    mini-plans (plan.py Obj*Plan) evaluated against the path's
    object-major columns, scatter-OR'd back to parents.  Scoring is
    constant (the reference's score_mode=none; avg/sum/max degrade to it
    — inner BM25 scoring inside nested blocks is not modeled)."""
    ft = ctx.field_type(q.path)
    if ft is None or ft.dv_kind != "nested":
        if q.ignore_unmapped:
            return _none()
        raise IllegalArgumentError(
            f"[nested] failed to find nested object under path "
            f"[{q.path}]")
    inner, ibind = _compile_obj(q.query, q.path, ctx)
    return (P.NestedPlan(path=q.path, inner=inner),
            {"inner": ibind, "boost": q.boost})


def _compile_obj(node, path, ctx):
    """Inner (object-space) compiler for nested queries."""
    prefix = path + "."

    def child_ft(field):
        if not field.startswith(prefix):
            field = prefix + field       # accept relative child names
        ft = ctx.field_type(field)
        if ft is None:
            raise IllegalArgumentError(
                f"[nested] unknown field [{field}] under [{path}]")
        return field, ft

    if isinstance(node, dsl.MatchAllQuery) or node is None:
        return P.ObjMatchAllPlan(), {}
    if isinstance(node, (dsl.TermQuery, dsl.TermsQuery)):
        raw = ([node.value] if isinstance(node, dsl.TermQuery)
               else list(node.values))
        field, ft = child_ft(node.field)
        if ft.dv_kind in ("long", "double"):
            return (P.ObjTermsPlan(field=field, kind="numeric"),
                    {"values": [float(ft.doc_value(v)) for v in raw]})
        return (P.ObjTermsPlan(field=field, kind="ordinal"),
                {"values": [str(ft.term_for_query(v)) for v in raw]})
    if isinstance(node, dsl.MatchQuery):
        field, ft = child_ft(node.field)
        if hasattr(ft, "search_terms"):
            terms = ft.search_terms(str(node.query), ctx.mapper.analyzers)
            return (P.ObjTermsPlan(field=field, kind="ordinal"),
                    {"values": terms})
        if ft.dv_kind in ("long", "double"):
            return (P.ObjTermsPlan(field=field, kind="numeric"),
                    {"values": [float(ft.doc_value(node.query))]})
        return (P.ObjTermsPlan(field=field, kind="ordinal"),
                {"values": [str(ft.term_for_query(node.query))]})
    if isinstance(node, dsl.RangeQuery):
        field, ft = child_ft(node.field)
        if ft.dv_kind not in ("long", "double"):
            raise IllegalArgumentError(
                f"[nested] range over [{field}] requires a numeric/date "
                "child field")
        def conv(v):
            return float(ft.doc_value(v))
        lo = conv(node.gte) if node.gte is not None else (
            conv(node.gt) if node.gt is not None else -np.inf)
        hi = conv(node.lte) if node.lte is not None else (
            conv(node.lt) if node.lt is not None else np.inf)
        return (P.ObjRangePlan(field=field,
                               include_lo=node.gt is None,
                               include_hi=node.lt is None),
                {"lo": lo, "hi": hi})
    if isinstance(node, dsl.ExistsQuery):
        field, _ft = child_ft(node.field)
        return P.ObjExistsPlan(field=field), {}
    if isinstance(node, dsl.BoolQuery):
        groups = []
        binds = []
        for clause_list in (node.must + node.filter, node.should,
                            node.must_not):
            compiled = [_compile_obj(c, path, ctx) for c in clause_list]
            groups.append(tuple(p for p, _b in compiled))
            binds.extend(b for _p, b in compiled)
        required = calc_min_should_match(
            len(node.should),
            node.minimum_should_match
            if node.minimum_should_match is not None
            else (0 if (node.must or node.filter) else 1))
        return (P.ObjBoolPlan(must=groups[0], should=groups[1],
                              must_not=groups[2],
                              should_required=required >= 1),
                {"children": tuple(binds)})
    raise IllegalArgumentError(
        f"[nested] inner query type [{type(node).__name__}] is not "
        "supported — use term/terms/match/range/exists/bool")


def _c_boosting(q, ctx, scored):
    pos_p, pos_b = compile_query(q.positive, ctx, scored)
    neg_p, neg_b = compile_query(q.negative, ctx, scored=False)
    return (P.BoostingPlan(positive=pos_p, negative=neg_p),
            {"boost": q.boost, "negative_boost": q.negative_boost,
             "children": (pos_b, neg_b)})


def _c_terms_set(q, ctx, scored):
    ft = _require_ft(ctx, q.field, "terms_set")
    if ft is None:
        return _none()
    msm_ft = ctx.field_type(q.minimum_should_match_field)
    if msm_ft is None or msm_ft.dv_kind not in ("long", "double"):
        raise IllegalArgumentError(
            f"[terms_set] minimum_should_match_field "
            f"[{q.minimum_should_match_field}] must be a numeric field")
    terms = [ft.term_for_query(t) for t in q.terms]
    if not terms:
        return _none()
    return (P.TermsSetPlan(field=q.field,
                           msm_field=q.minimum_should_match_field,
                           scored=scored),
            {"terms": tuple(terms),
             "idfs": _idfs_for(ctx, q.field, terms),
             "weights": np.full(len(terms), q.boost, np.float32),
             "avgdl": ctx.field_stats(q.field).avgdl})


def _c_distance_feature(q, ctx, scored):
    from opensearch_tpu.search.query_dsl import (parse_distance_m,
                                                 parse_geo_point)

    ft = _require_ft(ctx, q.field, "distance_feature")
    if ft is None:
        return _none()
    if ft.dv_kind == "geo_point":
        origin = parse_geo_point(q.origin)
        pivot = parse_distance_m(q.pivot)
        kind = "geo"
    elif ft.type_name in ("date", "date_nanos"):
        from opensearch_tpu.search.aggs import _parse_duration_ms
        origin = float(parse_date_millis(q.origin))
        pivot = float(_parse_duration_ms(q.pivot)
                      if isinstance(q.pivot, str) else q.pivot)
        kind = "numeric"
    elif ft.dv_kind in ("long", "double"):
        origin = float(q.origin)
        pivot = float(q.pivot)
        kind = "numeric"
    else:
        raise IllegalArgumentError(
            f"[distance_feature] field [{q.field}] must be date, numeric "
            f"or geo_point, got [{ft.type_name}]")
    if pivot <= 0:
        raise IllegalArgumentError("[distance_feature] pivot must be > 0")
    return (P.DistanceFeaturePlan(field=q.field, kind=kind),
            {"origin": origin, "pivot": pivot, "boost": q.boost})


def _c_geo_distance(q, ctx, scored):
    from opensearch_tpu.search.query_dsl import parse_distance_m

    ft = _require_ft(ctx, q.field, "geo_distance")
    if ft is None:
        return _none()
    if ft.dv_kind != "geo_point":
        raise IllegalArgumentError(
            f"[geo_distance] field [{q.field}] is not a geo_point")
    return (P.GeoDistancePlan(field=q.field),
            {"lat": q.lat, "lon": q.lon,
             "distance_m": parse_distance_m(q.distance), "boost": q.boost})


def _c_geo_bounding_box(q, ctx, scored):
    ft = _require_ft(ctx, q.field, "geo_bounding_box")
    if ft is None:
        return _none()
    if ft.dv_kind != "geo_point":
        raise IllegalArgumentError(
            f"[geo_bounding_box] field [{q.field}] is not a geo_point")
    return (P.GeoBoxPlan(field=q.field),
            {"top": q.top, "left": q.left, "bottom": q.bottom,
             "right": q.right, "boost": q.boost})


def _c_geo_polygon(q, ctx, scored):
    ft = _require_ft(ctx, q.field, "geo_polygon")
    if ft is None:
        return _none()
    if ft.dv_kind != "geo_point":
        raise IllegalArgumentError(
            f"[geo_polygon] field [{q.field}] is not a geo_point")
    return (P.GeoPolygonPlan(field=q.field),
            {"lats": [p[0] for p in q.points],
             "lons": [p[1] for p in q.points], "boost": q.boost})


def _expand_prefix_terms(ctx, field, prefix: str, max_expansions: int):
    """Terms with ``prefix`` across all segments (sorted dictionaries =
    binary-searched range per segment), capped like MultiTermQuery's
    max_expansions."""
    import bisect

    out: list[str] = []
    seen: set = set()
    for seg in ctx.segments:
        pf = seg.postings.get(field)
        if pf is None:
            continue
        sterms = ctx.sorted_terms(seg, field)
        lo = bisect.bisect_left(sterms, prefix)
        for i in range(lo, len(sterms)):
            t = sterms[i]
            if not t.startswith(prefix):
                break
            if t not in seen:
                seen.add(t)
                out.append(t)
            if len(out) >= max_expansions:
                return out
    return out


def _phrase_from_tokens(ctx, field, terms, positions, boost, scored):
    """PhrasePlan bind straight from (term, position) tokens — keeps the
    analyzer's position gaps (stopword holes) intact."""
    if len(terms) == 1:
        return _term_bag(ctx, field, [terms[0]], 1, boost, scored)
    stats = ctx.field_stats(field)
    idf_sum = float(np.sum(_idfs_for(ctx, field, terms)))
    bind = {"terms": tuple(terms), "positions": tuple(positions),
            "idf_sum": idf_sum, "boost": boost, "avgdl": stats.avgdl}
    return P.PhrasePlan(field=field, scored=scored), bind


def _c_match_phrase_prefix(q, ctx, scored):
    """Phrase whose LAST token is a prefix: expand it against the term
    dictionary and dis-max the resulting phrases, substituting the last
    term IN PLACE so original token positions (incl. stopword gaps)
    survive (MatchPhrasePrefixQueryBuilder -> MultiPhrasePrefixQuery)."""
    ft = _require_ft(ctx, q.field, "match_phrase_prefix")
    if ft is None:
        return _none()
    if not isinstance(ft, TextFieldType):
        return _c_term(dsl.TermQuery(field=q.field, value=q.query,
                                     boost=q.boost), ctx, scored)
    analyzer = ctx.mapper.analyzers.get(ft.search_analyzer_name)
    toks = analyzer.analyze(str(q.query))
    if not toks:
        return _none()
    if q.slop:
        raise IllegalArgumentError(
            "match_phrase_prefix slop > 0 is not supported yet")
    terms = [t.term for t in toks]
    positions = [t.position for t in toks]
    expansions = _expand_prefix_terms(ctx, q.field, terms[-1],
                                      int(q.max_expansions))
    if not expansions:
        return _none()
    plans, binds = [], []
    for t in expansions:
        p, b = _phrase_from_tokens(ctx, q.field, terms[:-1] + [t],
                                   positions, q.boost, scored)
        plans.append(p)
        binds.append(b)
    if len(plans) == 1:
        return plans[0], binds[0]
    return (P.DisMaxPlan(children=tuple(plans)),
            {"boost": 1.0, "tie_breaker": 0.0, "children": tuple(binds)})


def _c_match_bool_prefix(q, ctx, scored):
    """Every token a term clause, the last a prefix clause, combined as
    a bool (MatchBoolPrefixQueryBuilder)."""
    ft = _require_ft(ctx, q.field, "match_bool_prefix")
    if ft is None:
        return _none()
    if not isinstance(ft, TextFieldType):
        return _c_term(dsl.TermQuery(field=q.field, value=q.query,
                                     boost=q.boost), ctx, scored)
    analyzer_name = getattr(q, "analyzer", None)
    if analyzer_name:
        terms = ctx.mapper.analyzers.get(analyzer_name).terms(
            str(q.query))
    else:
        terms = ft.search_terms(str(q.query), ctx.mapper.analyzers)
    if not terms:
        return _none()
    fuzz = getattr(q, "fuzziness", None)
    if fuzz is not None:
        clauses = [dsl.FuzzyQuery(field=q.field, value=t,
                                  fuzziness=fuzz) for t in terms[:-1]]
    else:
        clauses = [dsl.TermQuery(field=q.field, value=t)
                   for t in terms[:-1]]
    expansions = _expand_prefix_terms(ctx, q.field, terms[-1],
                                      int(q.max_expansions))
    if expansions:
        # capped dictionary expansion, like the phrase-prefix sibling
        clauses.append(dsl.TermsQuery(field=q.field, values=expansions)
                       if len(expansions) > 1
                       else dsl.TermQuery(field=q.field,
                                          value=expansions[0]))
    elif not clauses:
        return _none()
    # an unexpandable prefix contributes nothing; other clauses (e.g.
    # fuzzy terms) still match under OR semantics
    if q.operator == "and":
        return compile_query(dsl.BoolQuery(must=clauses, boost=q.boost),
                             ctx, scored)
    msm = getattr(q, "minimum_should_match", None) or "1"
    return compile_query(dsl.BoolQuery(should=clauses,
                                       minimum_should_match=str(msm),
                                       boost=q.boost), ctx, scored)


def _positive_float(v, what: str) -> float:
    try:
        f = float(v)
    except (TypeError, ValueError):
        raise ParsingError(
            f"[rank_feature] {what} must be a number, got [{v}]") from None
    if not math.isfinite(f) or f <= 0:
        raise ParsingError(
            f"[rank_feature] {what} must be positive, got [{v}]")
    return f


def _c_rank_feature(q, ctx, scored):
    """rank_feature scoring lowered onto the script-score plan: the
    saturation/log/sigmoid curves are exactly the painless-subset
    expressions over doc['f'].value (RankFeatureQueryBuilder; the
    feature column is a positive numeric doc value)."""
    ft = _require_ft(ctx, q.field, "rank_feature")
    if ft is None:
        return _none()
    if ft.dv_kind not in ("long", "double"):
        raise IllegalArgumentError(
            f"[rank_feature] field [{q.field}] must be numeric "
            f"(rank_feature type), got [{ft.type_name}]")
    f = f"doc['{q.field}'].value"
    if q.log is not None:
        scaling = float(q.log.get("scaling_factor", 1.0))
        src = f"Math.log({scaling} + {f})"
    elif q.sigmoid is not None:
        if "pivot" not in q.sigmoid or "exponent" not in q.sigmoid:
            raise ParsingError(
                "[rank_feature] sigmoid requires [pivot] and [exponent]")
        pivot = _positive_float(q.sigmoid["pivot"], "sigmoid pivot")
        exp = _positive_float(q.sigmoid["exponent"], "sigmoid exponent")
        src = (f"Math.pow({f}, {exp}) / "
               f"(Math.pow({f}, {exp}) + Math.pow({pivot}, {exp}))")
    else:
        pivot = (q.saturation or {}).get("pivot")
        if pivot is not None:
            pivot = _positive_float(pivot, "saturation pivot")
        if pivot is None:
            # default pivot ~ the field's mean positive value (the
            # reference uses an approximate geometric mean)
            total, count = 0.0, 0
            for seg in ctx.segments:
                dv = seg.numeric_dv.get(q.field)
                if dv is not None and len(dv.values):
                    total += float(np.sum(dv.values))
                    count += int(len(dv.values))
            pivot = (total / count) if count else 1.0
        src = f"{f} / ({f} + {float(pivot)})"
    return compile_query(dsl.ScriptScoreQuery(
        query=dsl.ExistsQuery(field=q.field),
        script={"source": src}, boost=q.boost), ctx, scored)


# span end disabled: any analyzer position is < this (< ops.phrase
# POS_BASE so doc*POS_BASE+pos arithmetic can't overflow)
_SPAN_NO_END = 1 << 21


def _span_near_state(ctx, field, terms, *, slop, ordered, end, boost,
                     scored):
    stats = ctx.field_stats(field)
    idf_sum = float(np.sum(_idfs_for(ctx, field, terms)))
    bind = {"terms": tuple(terms), "slop": int(slop), "end": int(end),
            "idf_sum": idf_sum, "boost": boost, "avgdl": stats.avgdl}
    return P.SpanNearPlan(field=field, ordered=ordered,
                          scored=scored), bind


def _c_span_term(q, ctx, scored):
    ft = _require_ft(ctx, q.field, "span_term")
    if ft is None:
        return _none()
    return _term_bag(ctx, q.field, [str(q.value)], 1, q.boost, scored)


def _span_clause_terms(clauses, qname):
    """Validate span sub-clauses: span_term only, one shared field."""
    field, terms = None, []
    for c in clauses:
        if not isinstance(c, dsl.SpanTermQuery):
            raise IllegalArgumentError(
                f"[{qname}] supports span_term clauses only, got "
                f"[{type(c).__name__}]")
        if field is None:
            field = c.field
        elif c.field != field:
            raise IllegalArgumentError(
                f"[{qname}] clauses must target a single field, got "
                f"[{field}] and [{c.field}]")
        terms.append(str(c.value))
    return field, terms


def _c_span_near(q, ctx, scored):
    field, terms = _span_clause_terms(q.clauses, "span_near")
    ft = _require_ft(ctx, field, "span_near")
    if ft is None:
        return _none()
    if len(terms) == 1:
        return _term_bag(ctx, field, terms, 1, q.boost, scored)
    if not q.in_order and len(terms) > 2:
        raise IllegalArgumentError(
            "[span_near] with [in_order]=false supports at most 2 "
            "clauses (unordered minimal-window matching beyond pairs "
            "is not implemented)")
    return _span_near_state(ctx, field, terms, slop=q.slop,
                            ordered=q.in_order, end=_SPAN_NO_END,
                            boost=q.boost, scored=scored)


def _c_span_first(q, ctx, scored):
    # restricted to a span_term match so 'span ends before [end]'
    # is exact (a single term at pos occupies [pos, pos+1))
    if not isinstance(q.match, dsl.SpanTermQuery):
        raise IllegalArgumentError(
            "[span_first] supports a span_term [match] only")
    ft = _require_ft(ctx, q.match.field, "span_first")
    if ft is None:
        return _none()
    return _span_near_state(ctx, q.match.field, [str(q.match.value)],
                            slop=0, ordered=True, end=q.end,
                            boost=q.boost, scored=scored)


def _c_span_or(q, ctx, scored):
    _span_clause_terms(q.clauses, "span_or")   # validation only
    return compile_query(
        dsl.BoolQuery(should=list(q.clauses), minimum_should_match="1",
                      boost=q.boost), ctx, scored)


def _c_intervals(q, ctx, scored):
    """intervals: match / any_of / all_of rules (ref
    IntervalQueryBuilder.java:43).  match compiles to the span kernel;
    any_of is a should-of-1; all_of with unbounded gaps and no order is
    positionless AND, otherwise its sub-rules must be single terms so it
    flattens to one ordered/unordered near."""
    ft = _require_ft(ctx, q.field, "intervals")
    if ft is None:
        return _none()

    def rule_terms(rule):
        m = rule.get("match")
        if m is None or not isinstance(m, dict):
            return None
        analyzer = ctx.mapper.analyzers.get(ft.search_analyzer_name)
        return [t.term for t in analyzer.analyze(str(m.get("query", "")))]

    def compile_rule(rule):
        if len(rule) != 1:
            raise IllegalArgumentError(
                f"[intervals] rule must have exactly one key, got "
                f"{sorted(rule)}")
        kind, body = next(iter(rule.items()))
        allowed = {"match": {"query", "ordered", "max_gaps", "mode"},
                   "any_of": {"intervals"},
                   "all_of": {"intervals", "ordered", "max_gaps",
                              "mode"}}
        if kind in allowed and isinstance(body, dict):
            extra = set(body) - allowed[kind]
            if extra:
                # silently dropping filter/analyzer/use_field/... would
                # return over-broad results — reject like every other
                # unsupported interval feature
                raise IllegalArgumentError(
                    f"[intervals] [{kind}] options {sorted(extra)} are "
                    f"not supported — supported: "
                    f"{sorted(allowed[kind])}")
        if kind == "match":
            terms = rule_terms(rule)
            if not terms:
                return _none()
            mode = body.get("mode")
            ordered = (mode == "ordered" if mode is not None
                       else bool(body.get("ordered", False)))
            max_gaps = int(body.get("max_gaps", -1))
            if len(terms) == 1:
                return _term_bag(ctx, q.field, terms, 1, q.boost, scored)
            if max_gaps < 0 and not ordered:
                return compile_query(dsl.BoolQuery(must=[
                    dsl.TermQuery(field=q.field, value=t)
                    for t in terms]), ctx, scored)
            if not ordered and len(terms) > 2:
                raise IllegalArgumentError(
                    "[intervals] unordered [match] with [max_gaps] "
                    "supports at most 2 terms")
            slop = max_gaps if max_gaps >= 0 else _SPAN_NO_END
            return _span_near_state(ctx, q.field, terms, slop=slop,
                                    ordered=ordered, end=_SPAN_NO_END,
                                    boost=q.boost, scored=scored)
        if kind in ("any_of", "all_of"):
            subs = body.get("intervals") or []
            if not subs:
                raise IllegalArgumentError(
                    f"[intervals] [{kind}] requires [intervals]")
            if body.get("mode") is not None:
                body = {**body, "ordered": body["mode"] == "ordered"}
            if kind == "all_of" and (body.get("ordered")
                                     or int(body.get("max_gaps", -1)) >= 0):
                # positional all_of flattens iff every sub-rule is a
                # single-term match
                flat = [rule_terms(s) for s in subs]
                if any(t is None or len(t) != 1 for t in flat):
                    raise IllegalArgumentError(
                        "[intervals] [all_of] with [ordered]/[max_gaps] "
                        "supports single-term [match] sub-rules only")
                terms = [t[0] for t in flat]
                ordered = bool(body.get("ordered", False))
                max_gaps = int(body.get("max_gaps", -1))
                if not ordered and len(terms) > 2:
                    raise IllegalArgumentError(
                        "[intervals] unordered [all_of] with [max_gaps] "
                        "supports at most 2 sub-rules")
                slop = max_gaps if max_gaps >= 0 else _SPAN_NO_END
                return _span_near_state(ctx, q.field, terms, slop=slop,
                                        ordered=ordered,
                                        end=_SPAN_NO_END,
                                        boost=q.boost, scored=scored)
            wrapped = [dsl.IntervalsQuery(field=q.field, rule=s)
                       for s in subs]
            if kind == "any_of":
                return compile_query(
                    dsl.BoolQuery(should=wrapped,
                                  minimum_should_match="1",
                                  boost=q.boost), ctx, scored)
            return compile_query(dsl.BoolQuery(must=wrapped,
                                               boost=q.boost),
                                 ctx, scored)
        if kind in ("prefix", "wildcard", "regexp"):
            # multi-term rules expand against the term dictionary and
            # compile as a should-of-1 over the expansions
            # (IntervalsSourceProvider's Prefix/Wildcard/Regexp; the
            # reference has no fuzzy interval source, so `fuzzy` — an
            # edit-distance expansion with no positional semantics here —
            # is rejected below rather than silently over-matching)
            import re as _re

            if kind == "prefix":
                pat = str(body.get("prefix", ""))
                terms = _expand_prefix_terms(ctx, q.field, pat, 128)
            else:
                pat = str(body.get("pattern", ""))
                flags = (_re.IGNORECASE
                         if body.get("case_insensitive") else 0)
                if kind == "wildcard":
                    import fnmatch
                    rx = _re.compile(fnmatch.translate(pat), flags)
                else:
                    rx = _re.compile(pat, flags)
                terms = []
                seen = set()
                for seg in ctx.segments:
                    if q.field not in seg.postings:
                        continue
                    for t in ctx.sorted_terms(seg, q.field):
                        if t not in seen and rx.fullmatch(t):
                            seen.add(t)
                            terms.append(t)
                        if len(terms) >= 128:
                            break
            if not terms:
                return _none()
            return compile_query(dsl.BoolQuery(
                should=[dsl.TermQuery(field=q.field, value=t)
                        for t in terms],
                minimum_should_match="1", boost=q.boost), ctx, scored)
        raise IllegalArgumentError(
            f"[intervals] unsupported rule [{kind}] — supported: "
            "match, any_of, all_of, prefix, wildcard, regexp")

    return compile_rule(q.rule)


_DECAY_FNS = ("gauss", "exp", "linear")


def _c_function_score(q, ctx, scored):
    """function_score: per-function specs compile to static FunctionSpec
    structure + dynamic param binds (functionscore/ dir; decay, fvf,
    random_score, weight, script_score functions)."""
    from opensearch_tpu.search.query_dsl import (parse_distance_m,
                                                 parse_geo_point,
                                                 parse_query)
    from opensearch_tpu.search.scripting import compile_score_script

    child = q.query if q.query is not None else dsl.MatchAllQuery()
    cplan, cbind = compile_query(child, ctx, scored=True)
    specs, binds = [], []
    for f in q.functions:
        f = dict(f)
        fbind = {}
        fplan = None
        if f.get("filter") is not None:
            fplan, fb = compile_query(parse_query(f["filter"]), ctx,
                                      scored=False)
            fbind["filter"] = fb
        if "weight" in f:
            fbind["weight"] = float(f["weight"])
        decay_fn = next((d for d in _DECAY_FNS if d in f), None)
        if "field_value_factor" in f:
            fvf = f["field_value_factor"]
            field = fvf.get("field")
            ft = ctx.field_type(field or "")
            if ft is None or ft.dv_kind not in ("long", "double"):
                raise IllegalArgumentError(
                    f"[field_value_factor] field [{field}] must be "
                    "numeric")
            specs.append(P.FunctionSpec(
                kind="field_value_factor", filter=fplan, field=field,
                modifier=str(fvf.get("modifier", "none")).lower()))
            fbind.update({"factor": float(fvf.get("factor", 1.0)),
                          "missing": float(fvf.get("missing", 1.0))})
        elif "random_score" in f:
            rs = f.get("random_score") or {}
            specs.append(P.FunctionSpec(kind="random_score",
                                        filter=fplan))
            fbind["seed"] = float(rs.get("seed", 0))
        elif "script_score" in f:
            program = compile_score_script(
                (f["script_score"] or {}).get("script") or {})
            specs.append(P.FunctionSpec(kind="script_score",
                                        filter=fplan, program=program))
        elif decay_fn is not None:
            body = f[decay_fn]
            ((field, conf),) = tuple(body.items()) if len(body) == 1 \
                else (_raise_decay(),)
            ft = ctx.field_type(field)
            if ft is None:
                return _none()
            if ft.dv_kind == "geo_point":
                lat, lon = parse_geo_point(conf["origin"])
                fbind.update({"origin_lat": lat, "origin_lon": lon,
                              "scale": parse_distance_m(conf["scale"]),
                              "offset": parse_distance_m(
                                  conf.get("offset", 0))})
                geo = True
            elif ft.type_name == "date":
                from opensearch_tpu.search.aggs import _parse_duration_ms

                def dur(v):
                    return float(_parse_duration_ms(v)
                                 if isinstance(v, str) else v)
                fbind.update({
                    "origin": float(parse_date_millis(conf["origin"])),
                    "scale": dur(conf["scale"]),
                    "offset": dur(conf.get("offset", 0))})
                geo = False
            elif ft.dv_kind in ("long", "double"):
                fbind.update({"origin": float(conf["origin"]),
                              "scale": float(conf["scale"]),
                              "offset": float(conf.get("offset", 0))})
                geo = False
            else:
                raise IllegalArgumentError(
                    f"[{decay_fn}] field [{field}] must be numeric, "
                    "date or geo_point")
            if fbind["scale"] <= 0:
                raise IllegalArgumentError(
                    f"[{decay_fn}] scale must be > 0")
            fbind["decay"] = float(conf.get("decay", 0.5))
            if not (0.0 < fbind["decay"] < 1.0):
                raise IllegalArgumentError(
                    f"[{decay_fn}] decay must be in (0, 1)")
            specs.append(P.FunctionSpec(kind="decay", filter=fplan,
                                        field=field, decay_fn=decay_fn,
                                        geo=geo))
        elif "weight" in f:
            specs.append(P.FunctionSpec(kind="weight", filter=fplan))
        else:
            raise IllegalArgumentError(
                f"unknown function_score function {sorted(f)}")
        binds.append(fbind)
    if q.score_mode not in ("multiply", "sum", "avg", "first", "max",
                            "min"):
        raise IllegalArgumentError(
            f"unknown score_mode [{q.score_mode}]")
    if q.boost_mode not in ("multiply", "replace", "sum", "avg", "max",
                            "min"):
        raise IllegalArgumentError(
            f"unknown boost_mode [{q.boost_mode}]")
    return (P.FunctionScorePlan(child=cplan, functions=tuple(specs),
                                score_mode=q.score_mode,
                                boost_mode=q.boost_mode),
            {"child": cbind, "functions": tuple(binds), "boost": q.boost,
             "max_boost": q.max_boost, "min_score": q.min_score})


def _raise_decay():
    raise IllegalArgumentError(
        "decay function must name exactly one field")


def _c_more_like_this(q, ctx, scored):
    """more_like_this: host-side tf-idf term selection over the like
    texts/docs, compiled as a should term-bag (MoreLikeThisQueryBuilder's
    interesting-terms selection)."""
    fields = q.fields
    if not fields:
        fields = [f for f, ft in ctx.mapper.field_types().items()
                  if isinstance(ft, TextFieldType)]
    if not fields:
        return _none()
    texts: list[str] = []
    liked_ids: list[str] = []
    for item in q.like:
        if isinstance(item, dict):
            doc_id = item.get("_id")
            src = None
            for seg in ctx.segments:
                local = seg.id_to_local.get(str(doc_id))
                if local is not None:
                    src = seg.source(local)
                    break
            if src is None:
                continue
            liked_ids.append(str(doc_id))
            for f in fields:
                v = src.get(f)
                if isinstance(v, str):
                    texts.append(v)
        else:
            texts.append(str(item))
    if not texts:
        return _none()
    clauses = []
    for field in fields:
        ft = ctx.field_type(field)
        if not isinstance(ft, TextFieldType):
            continue
        tf: dict[str, int] = {}
        for text in texts:
            for t in ft.search_terms(text, ctx.mapper.analyzers):
                tf[t] = tf.get(t, 0) + 1
        n_docs = max(ctx.field_stats(field).doc_count, 1)
        cands = []
        for t, freq in tf.items():
            if freq < q.min_term_freq:
                continue
            df = ctx.df(field, t)
            if df < q.min_doc_freq:
                continue
            idf = np.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))
            cands.append((freq * idf, t))
        cands.sort(key=lambda x: (-x[0], x[1]))
        terms = [t for _s, t in cands[: q.max_query_terms]]
        if terms:
            required = max(1, calc_min_should_match(
                len(terms), q.minimum_should_match))
            clauses.append(_term_bag(ctx, field, terms, required,
                                     q.boost, scored))
    if not clauses:
        return _none()
    if len(clauses) == 1 and not liked_ids:
        return clauses[0]
    # the liked input docs are EXCLUDED unless include:true (the
    # reference's default — a doc is trivially most-like itself)
    must_not = ()
    if liked_ids and not q.include:
        must_not = (compile_query(dsl.IdsQuery(values=liked_ids), ctx,
                                  scored=False),)
    plans = tuple(p for p, _b in clauses)
    return (P.BoolPlan(should=plans,
                       must_not=tuple(p for p, _b in must_not)),
            {"boost": 1.0, "required": 1,
             "children": (tuple(b for _p, b in clauses)
                          + tuple(b for _p, b in must_not))})


def _c_script_score(q, ctx, scored):
    """script_score: the child query's matched set rescored by a compiled
    jnp expression (search/scripting.py); BASELINE config #2's
    knn-via-script shape lowers onto the exact-knn kernels.  Unknown or
    unsupported scripts raise ScriptException -> a clean 400."""
    from opensearch_tpu.search.scripting import (ScriptException,
                                                 compile_score_script)

    program = compile_score_script(q.script)
    for f in program.numeric_fields:
        ft = ctx.field_type(f)
        if ft is not None and ft.dv_kind not in ("long", "double"):
            raise ScriptException(
                f"doc['{f}'].value requires a numeric/date field, "
                f"[{f}] is [{ft.type_name}]")
    for f in program.vector_fields:
        ft = ctx.field_type(f)
        if ft is not None and ft.dv_kind != "vector":
            raise ScriptException(
                f"vector function over [{f}] requires a knn_vector "
                f"field, got [{ft.type_name}]")
    child = q.query if q.query is not None else dsl.MatchAllQuery()
    cplan, cbind = compile_query(child, ctx, scored=program.uses_score)
    return (P.ScriptScorePlan(child=cplan, program=program),
            {"child": cbind, "boost": q.boost, "min_score": q.min_score})


_COMPILERS = {
    dsl.MatchAllQuery: _c_match_all,
    dsl.MatchNoneQuery: _c_match_none,
    dsl.TermQuery: _c_term,
    dsl.TermsQuery: _c_terms,
    dsl.MatchQuery: _c_match,
    dsl.MatchPhraseQuery: _c_match_phrase,
    dsl.MultiMatchQuery: _c_multi_match,
    dsl.BoolQuery: _c_bool,
    dsl.RangeQuery: _c_range,
    dsl.ExistsQuery: _c_exists,
    dsl.IdsQuery: _c_ids,
    dsl.HasChildQuery: _c_has_child,
    dsl.HasParentQuery: _c_has_parent,
    dsl.ParentIdQuery: _c_parent_id,
    dsl.PrefixQuery: _c_prefix,
    dsl.WildcardQuery: _c_wildcard,
    dsl.RegexpQuery: _c_regexp,
    dsl.FuzzyQuery: _c_fuzzy,
    dsl.ConstantScoreQuery: _c_constant_score,
    dsl.DisMaxQuery: _c_dis_max,
    dsl.SimpleQueryStringQuery: _c_simple_query_string,
    dsl.KnnQuery: _c_knn,
    dsl.ScriptScoreQuery: _c_script_score,
    dsl.BoostingQuery: _c_boosting,
    dsl.NestedQuery: _c_nested,
    dsl.PercolateQuery: _c_percolate,
    dsl.TermsSetQuery: _c_terms_set,
    dsl.DistanceFeatureQuery: _c_distance_feature,
    dsl.FunctionScoreQuery: _c_function_score,
    dsl.MoreLikeThisQuery: _c_more_like_this,
    dsl.GeoDistanceQuery: _c_geo_distance,
    dsl.GeoPolygonQuery: _c_geo_polygon,
    dsl.MatchPhrasePrefixQuery: _c_match_phrase_prefix,
    dsl.MatchBoolPrefixQuery: _c_match_bool_prefix,
    dsl.RankFeatureQuery: _c_rank_feature,
    dsl.GeoBoundingBoxQuery: _c_geo_bounding_box,
    dsl.SpanTermQuery: _c_span_term,
    dsl.SpanNearQuery: _c_span_near,
    dsl.SpanFirstQuery: _c_span_first,
    dsl.SpanOrQuery: _c_span_or,
    dsl.IntervalsQuery: _c_intervals,
}
