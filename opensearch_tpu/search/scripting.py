"""Script scoring: a safe expression subset compiled to jnp programs.

The reference compiles Painless to JVM bytecode per script
(ref script/ScriptService.java:438, modules/lang-painless/.../
PainlessScriptEngine.java:139) and runs it doc-at-a-time inside the
collector.  The TPU formulation compiles the script ONCE into a pure
jnp expression over dense per-doc columns, so scoring stays a fused
vector program — no per-doc interpreter in the hot loop.

Supported surface (the score-context essentials):

- arithmetic / comparisons / ternaries over ``_score``, ``params.*``,
  and ``doc['field'].value`` (numeric doc values; missing -> 0.0, with
  ``doc['field'].size()`` for explicit missing checks);
- ``Math.log/log10/sqrt/exp/abs/min/max/pow/floor/ceil`` plus bare
  ``min/max/abs``;
- vector helpers matching the k-NN plugin's whitelist:
  ``cosineSimilarity(params.qv, doc['vec'])``,
  ``dotProduct(params.qv, doc['vec'])``,
  ``l2Squared(params.qv, doc['vec'])``, ``sigmoid(x)``;
- the plugin's pre-baked ``{"lang": "knn", "source": "knn_score"}``
  script (params: field / query_value / space_type) — BASELINE
  config #2's exact shape — lowered onto the same exact-knn kernel the
  ``knn`` query uses (ops/knn.py).

Anything outside the subset raises ``ScriptException`` (400), never an
engine crash: unknown scripts are a client error, not a server one.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field as dc_field
from typing import Optional

import numpy as np

import opensearch_tpu.common.jaxenv  # noqa: F401
import jax.numpy as jnp

from opensearch_tpu.common.errors import OpenSearchTpuError


class ScriptException(OpenSearchTpuError):
    status = 400


_MATH_FNS = {
    "log": jnp.log, "log10": jnp.log10, "sqrt": jnp.sqrt, "exp": jnp.exp,
    "abs": jnp.abs, "min": jnp.minimum, "max": jnp.maximum,
    "pow": jnp.power, "floor": jnp.floor, "ceil": jnp.ceil,
}
_BARE_FNS = {"min": jnp.minimum, "max": jnp.maximum, "abs": jnp.abs,
             "sigmoid": lambda x: 1.0 / (1.0 + jnp.exp(-x))}
_VECTOR_FNS = ("cosineSimilarity", "dotProduct", "l2Squared")


@dataclass(frozen=True)
class ScriptProgram:
    """Compiled script: hashable by (source, param NAMES) — not values —
    so every query vector / numeric param is a DYNAMIC program input and
    identical scripts share one XLA program across queries (the same
    static-structure/dynamic-binding split as the plan tree itself)."""

    source: str
    param_names: tuple                     # sorted numeric param names
    numeric_fields: tuple                  # doc['f'].value fields used
    vector_fields: tuple                   # doc['f'] vector fields used
    uses_score: bool
    _tree: object = dc_field(compare=False, hash=False, repr=False,
                             default=None)
    _params: dict = dc_field(compare=False, hash=False, repr=False,
                             default=None)

    def param_values(self):
        """Dynamic inputs in ``param_names`` order (host-side prepare)."""
        out = []
        for name in self.param_names:
            v = self._params[name]
            try:
                out.append(jnp.asarray(np.asarray(v, np.float32)))  # staging-ok: script literal
            except (ValueError, TypeError):
                raise ScriptException(
                    f"script param [{name}] is not numeric") from None
        return tuple(out)

    def eval(self, score, numeric_cols: dict, vector_cols: dict,
             param_vals: tuple):
        """Pure jnp evaluation; traced inside the plan's jitted eval."""
        params = dict(zip(self.param_names, param_vals))
        return _Evaluator(params, numeric_cols, vector_cols,
                          score).visit(self._tree)


class _FieldCollector(ast.NodeVisitor):
    """First pass: find doc[...] references and whether _score is used,
    and reject every node kind outside the whitelist."""

    _ALLOWED = (ast.Expression, ast.BinOp, ast.UnaryOp, ast.BoolOp,
                ast.Compare, ast.IfExp, ast.Call, ast.Attribute,
                ast.Subscript, ast.Name, ast.Constant, ast.Load,
                ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Mod, ast.Pow,
                ast.USub, ast.UAdd, ast.And, ast.Or, ast.Not,
                ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
                ast.List, ast.Tuple)

    def __init__(self):
        self.numeric: list[str] = []
        self.vectors: list[str] = []
        self.uses_score = False

    def generic_visit(self, node):
        if not isinstance(node, self._ALLOWED):
            raise ScriptException(
                f"unsupported script construct [{type(node).__name__}]")
        super().generic_visit(node)

    def visit_Name(self, node):
        if node.id == "_score":
            self.uses_score = True
        elif node.id not in ("doc", "params", "Math") and \
                node.id not in _BARE_FNS and node.id not in _VECTOR_FNS:
            raise ScriptException(f"unknown variable [{node.id}]")

    def visit_Call(self, node):
        fname = None
        if isinstance(node.func, ast.Name):
            fname = node.func.id
        if fname in _VECTOR_FNS:
            if len(node.args) != 2:
                raise ScriptException(f"[{fname}] takes (query, doc_field)")
            f = _doc_field_of(node.args[1])
            if f is None:
                raise ScriptException(
                    f"[{fname}] second argument must be doc['field']")
            self.vectors.append(f)
            self.visit(node.args[0])
            return
        self.generic_visit(node)

    def visit_Attribute(self, node):
        # doc['f'].value / doc['f'].size() / Math.fn / params.x
        f = _doc_field_of(node.value)
        if f is not None:
            if node.attr in ("value", "size"):
                self.numeric.append(f)
                return
            raise ScriptException(
                f"doc['{f}'].{node.attr} is not supported "
                "(use .value or .size())")
        self.generic_visit(node)


def _doc_field_of(node) -> Optional[str]:
    if (isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name) and node.value.id == "doc"):
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            return sl.value
    return None


class _Evaluator(ast.NodeVisitor):
    """Second pass: evaluate over jnp arrays (called inside the trace)."""

    def __init__(self, params, numeric_cols, vector_cols, score):
        self.params = params
        self.numeric = numeric_cols        # field -> (values, exists)
        self.vectors = vector_cols         # field -> (matrix, exists)
        self.score = score

    def visit(self, node):  # noqa: D102 — dispatch only
        fn = getattr(self, f"visit_{type(node).__name__}", None)
        if fn is None:
            raise ScriptException(
                f"unsupported script construct [{type(node).__name__}]")
        return fn(node)

    def visit_Expression(self, node):
        return self.visit(node.body)

    def visit_Constant(self, node):
        if isinstance(node.value, (int, float, bool)):
            return node.value
        raise ScriptException(
            f"unsupported literal [{node.value!r}] in score script")

    def visit_Name(self, node):
        if node.id == "_score":
            return self.score
        raise ScriptException(f"unknown variable [{node.id}]")

    def visit_List(self, node):
        return jnp.asarray([self.visit(e) for e in node.elts],  # staging-ok: script literal
                           jnp.float32)

    visit_Tuple = visit_List

    def _param(self, name):
        if name not in self.params:
            raise ScriptException(f"missing script param [{name}]")
        return self.params[name]

    def visit_Attribute(self, node):
        f = _doc_field_of(node.value)
        if f is not None and node.attr == "value":
            return self.numeric[f][0]
        if isinstance(node.value, ast.Name) and node.value.id == "params":
            return self._param(node.attr)
        raise ScriptException("unsupported attribute access in script")

    def visit_Subscript(self, node):
        if isinstance(node.value, ast.Name) and node.value.id == "params":
            sl = node.slice
            if isinstance(sl, ast.Constant):
                return self._param(sl.value)
        raise ScriptException("unsupported subscript in script")

    def visit_BinOp(self, node):
        a, b = self.visit(node.left), self.visit(node.right)
        op = type(node.op)
        if op is ast.Add:
            return a + b
        if op is ast.Sub:
            return a - b
        if op is ast.Mult:
            return a * b
        if op is ast.Div:
            return a / b
        if op is ast.Mod:
            return a % b
        if op is ast.Pow:
            return a ** b
        raise ScriptException("unsupported operator")

    def visit_UnaryOp(self, node):
        v = self.visit(node.operand)
        if isinstance(node.op, ast.USub):
            return -v
        if isinstance(node.op, ast.UAdd):
            return v
        if isinstance(node.op, ast.Not):
            return jnp.logical_not(v)
        raise ScriptException("unsupported unary operator")

    def visit_Compare(self, node):
        if len(node.ops) != 1:
            raise ScriptException("chained comparisons are not supported")
        a, b = self.visit(node.left), self.visit(node.comparators[0])
        op = type(node.ops[0])
        table = {ast.Eq: jnp.equal, ast.NotEq: jnp.not_equal,
                 ast.Lt: jnp.less, ast.LtE: jnp.less_equal,
                 ast.Gt: jnp.greater, ast.GtE: jnp.greater_equal}
        return table[op](a, b)

    def visit_BoolOp(self, node):
        vals = [self.visit(v) for v in node.values]
        out = vals[0]
        for v in vals[1:]:
            out = (jnp.logical_and(out, v) if isinstance(node.op, ast.And)
                   else jnp.logical_or(out, v))
        return out

    def visit_IfExp(self, node):
        return jnp.where(self.visit(node.test), self.visit(node.body),
                         self.visit(node.orelse))

    def visit_Call(self, node):
        if isinstance(node.func, ast.Name):
            name = node.func.id
            if name in _VECTOR_FNS:
                q = self.visit(node.args[0])
                f = _doc_field_of(node.args[1])
                vec, exists = self.vectors[f]
                dots = vec @ q
                if name == "dotProduct":
                    return dots
                if name == "l2Squared":
                    v2 = jnp.sum(vec * vec, axis=1)
                    return jnp.maximum(v2 - 2.0 * dots + jnp.dot(q, q), 0.0)
                norms = jnp.sqrt(jnp.sum(vec * vec, axis=1))
                qn = jnp.sqrt(jnp.dot(q, q))
                return dots / jnp.maximum(norms * qn, 1e-30)
            if name in _BARE_FNS:
                args = [self.visit(a) for a in node.args]
                try:
                    return _BARE_FNS[name](*args)
                except TypeError as e:
                    raise ScriptException(
                        f"bad arguments to [{name}]: {e}") from None
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            # doc['f'].size()
            f = _doc_field_of(recv)
            if f is not None and node.func.attr == "size":
                return self.numeric[f][1].astype(jnp.int32)
            if isinstance(recv, ast.Name) and recv.id == "Math":
                fn = _MATH_FNS.get(node.func.attr)
                if fn is None:
                    raise ScriptException(
                        f"Math.{node.func.attr} is not supported")
                try:
                    return fn(*[self.visit(a) for a in node.args])
                except TypeError as e:
                    raise ScriptException(
                        f"bad arguments to [Math.{node.func.attr}]: "
                        f"{e}") from None
        raise ScriptException("unsupported function call in script")


def _split_ternary(src: str):
    """Find the outermost Java ternary ``cond ? a : b`` (depth 0, outside
    quotes); returns (cond, a, b) or None."""
    depth = 0
    quote = None
    for i, ch in enumerate(src):
        if quote:
            if ch == quote:
                quote = None
            continue
        if ch in "'\"":
            quote = ch
        elif ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif ch == "?" and depth == 0:
            level = 1
            d2, q2 = 0, None
            for j in range(i + 1, len(src)):
                c2 = src[j]
                if q2:
                    if c2 == q2:
                        q2 = None
                    continue
                if c2 in "'\"":
                    q2 = c2
                elif c2 in "([{":
                    d2 += 1
                elif c2 in ")]}":
                    d2 -= 1
                elif c2 == "?" and d2 == 0:
                    level += 1
                elif c2 == ":" and d2 == 0:
                    level -= 1
                    if level == 0:
                        return src[:i], src[i + 1: j], src[j + 1:]
            raise ScriptException("unterminated ternary in script")
    return None


def _sub_outside_quotes(src: str, fn) -> str:
    """Apply ``fn`` to each maximal unquoted chunk, leaving quoted spans
    (doc['field'] names!) byte-for-byte intact."""
    out = []
    chunk_start = 0
    quote = None
    for i, ch in enumerate(src):
        if quote:
            if ch == quote:
                out.append(src[chunk_start: i + 1])
                chunk_start = i + 1
                quote = None
        elif ch in "'\"":
            out.append(fn(src[chunk_start: i]))
            chunk_start = i
            quote = ch
    if quote:
        raise ScriptException("unterminated string literal in script")
    out.append(fn(src[chunk_start:]))
    return "".join(out)


def _painless_to_python(src: str) -> str:
    """Painless/Java surface syntax -> the equivalent Python expression:
    ``?:`` ternaries, ``&&``/``||``/``!``, true/false/null literals.
    Substitutions never touch quoted spans, so field names like
    doc['true'] survive."""
    import re as _re

    t = _split_ternary(src)
    if t is not None:
        cond, a, b = t
        return (f"(({_painless_to_python(a)}) if "
                f"({_painless_to_python(cond)}) else "
                f"({_painless_to_python(b)}))")

    def repl(chunk: str) -> str:
        chunk = _re.sub(r"&&", " and ", chunk)
        chunk = _re.sub(r"\|\|", " or ", chunk)
        chunk = _re.sub(r"!(?![=])", " not ", chunk)
        chunk = _re.sub(r"\btrue\b", "True", chunk)
        chunk = _re.sub(r"\bfalse\b", "False", chunk)
        chunk = _re.sub(r"\bnull\b", "None", chunk)
        return chunk

    return _sub_outside_quotes(src, repl)


def compile_score_script(script: dict) -> ScriptProgram:
    """Parse + whitelist a score script; raises ScriptException (400) on
    anything outside the subset."""
    if not isinstance(script, dict):
        raise ScriptException("[script] must be an object")
    lang = script.get("lang", "painless")
    source = script.get("source") or script.get("inline") or ""
    params = script.get("params") or {}
    if lang == "knn" or source == "knn_score":
        # the k-NN plugin's pre-baked script (BASELINE config #2)
        field = params.get("field")
        qv = params.get("query_value")
        if not field or qv is None:
            raise ScriptException(
                "knn_score requires params.field and params.query_value")
        space = params.get("space_type", "l2")
        src = {"l2": f"1 / (1 + l2Squared(params.query_value, doc['{field}']))",
               "cosinesimil":
                   f"(1 + cosineSimilarity(params.query_value, doc['{field}'])) / 2",
               "innerproduct":
                   f"dotProduct(params.query_value, doc['{field}'])",
               }.get(space)
        if src is None:
            raise ScriptException(f"unknown space_type [{space}]")
        source = src
    elif lang not in ("painless", "expression"):
        raise ScriptException(f"script lang [{lang}] is not supported")
    if not source:
        raise ScriptException("script [source] is required")
    try:
        tree = ast.parse(_painless_to_python(source), mode="eval")
    except SyntaxError as e:
        raise ScriptException(f"script compile error: {e}") from None
    coll = _FieldCollector()
    coll.visit(tree)
    numeric_params = {k: v for k, v in params.items()
                      if isinstance(v, (int, float, bool, list, tuple))
                      and not isinstance(v, str)}
    return ScriptProgram(
        source=source, param_names=tuple(sorted(numeric_params)),
        numeric_fields=tuple(sorted(set(coll.numeric))),
        vector_fields=tuple(sorted(set(coll.vectors))),
        uses_score=coll.uses_score, _tree=tree, _params=numeric_params)
