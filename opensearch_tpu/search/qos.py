"""Per-tenant QoS + adaptive overload control: the loop closer.

PRs 4/6/7 added the overload knobs (admission permits, duress shed +
``search.replica_selection.shed_occupancy``, the PR-12 batcher window)
and PRs 9/10 added the measurements (flight-recorder breaches,
per-signature percentiles/interarrival/coalescability, per-client
``X-Opaque-Id`` attribution).  This module connects them:

- ``parse_tenant_shares`` turns the ``search.qos.tenant_shares``
  setting ("tenantA:4,tenantB:1") into the weighted admission shares
  ``SearchAdmissionController`` carves per tenant (unlabeled traffic
  shares a default pool weighted by ``search.qos.default_share``).

- ``QosController`` is the feedback half: an AIMD controller on an
  injectable clock that reads the *measured* overload evidence each
  tick — 429/shed deltas from the admission ledger, breach deltas from
  the flight recorder, the coalescability fraction from query
  insights, per-tenant attempt shares from the admission tenant
  ledger — and adapts three knob families:

  * ``search.replica_selection.shed_occupancy`` (the coordinator
    duress-shed threshold): multiplicative decrease under sustained
    client-visible rejections (shed earlier, relieve the collapse),
    additive recovery toward a ceiling when healthy (stop shedding
    traffic the fleet can absorb).
  * the continuous batcher's auto Δt window (``engine.AUTO_WINDOW_MS``,
    only while ``search.batcher.window_ms`` is 0 = auto): widened
    under pressure when the workload is measurably coalescable (more
    arrivals amortize into each dispatch), decayed back toward the
    configured base when healthy.
  * per-tenant admission penalties: the tenant dominating the window's
    admission attempts far beyond its weighted fair share — the noisy
    neighbor — gets its carved share multiplicatively squeezed (never
    below one permit), recovering additively once the pressure clears.

  Every adaptation appends an audit record (old -> new + the numeric
  evidence that triggered it) to a bounded ring surfaced in
  ``_nodes/stats`` ``qos`` and mirrored into the flight recorder, so a
  3am "why did the batch window grow" has a recorded answer.

Hysteresis: a knob only moves after ``hysteresis_ticks`` consecutive
hot (or healthy) evaluations, and AIMD keeps every move bounded — the
controller walks, it never jumps.  Deterministic under a seeded
workload: all decisions are pure functions of counter deltas on an
injectable clock (tests drive ``run_once`` directly on a fake clock).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from opensearch_tpu.common.errors import IllegalArgumentError

#: tenant labels are bounded strings (they become stats keys and
#: Prometheus label values via the bounded top-N path)
TENANT_LABEL_CHARS = 64

#: the pool every unlabeled (no X-Opaque-Id) or unlisted tenant draws
#: from, weighted by ``search.qos.default_share``
DEFAULT_POOL = "_default"


def tenant_label(opaque_id: Optional[str]) -> str:
    """Normalize an ``X-Opaque-Id`` into a bounded tenant label; the
    anonymous pool for unlabeled traffic."""
    if not opaque_id:
        return DEFAULT_POOL
    return str(opaque_id)[:TENANT_LABEL_CHARS]


def parse_tenant_shares(spec) -> dict:
    """``"tenantA:4,tenantB:1"`` -> ``{"tenantA": 4.0, "tenantB": 1.0}``
    (already-parsed dicts pass through).  Raises IllegalArgumentError on
    malformed entries so the settings validator rejects bad updates
    before they land."""
    if not spec:
        return {}
    if isinstance(spec, dict):
        items = spec.items()
    else:
        items = []
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            name, sep, weight = part.rpartition(":")
            if not sep or not name.strip():
                raise IllegalArgumentError(
                    f"malformed tenant share [{part}]; expected "
                    "<tenant>:<weight>[,<tenant>:<weight>...]")
            items.append((name.strip(), weight))
    out = {}
    for name, weight in items:
        try:
            w = float(weight)
        except (TypeError, ValueError):
            raise IllegalArgumentError(
                f"tenant [{name}] share [{weight}] is not a number")
        if w <= 0:
            raise IllegalArgumentError(
                f"tenant [{name}] share must be > 0, got [{w}]")
        out[str(name)[:TENANT_LABEL_CHARS]] = w
    return out


class QosController:
    """The AIMD feedback controller (module docstring).  ``run_once``
    is one deterministic evaluation; production paces it via
    ``maybe_tick()`` on the search dispatch path (the same pacing idiom
    as ``SearchBackpressureService``)."""

    def __init__(self, *, admission, insights, backpressure=None,
                 clock=time.monotonic, interval_s: float = 1.0,
                 audit_capacity: int = 64):
        self.admission = admission
        self.insights = insights
        #: SearchBackpressureService whose node_duress thresholds the
        #: controller may adapt under DEVICE duress (breaker trips /
        #: poisoned results) — the ROADMAP-7 leftover
        self.backpressure = backpressure
        self._clock = clock
        self.enabled = False
        self.interval_s = float(interval_s)
        # watermarks on the window's client-visible rejection fraction
        # (429s + coordinator sheds over admission attempts)
        self.high_watermark = 0.10
        self.low_watermark = 0.01
        #: consecutive hot/healthy evaluations before a knob moves
        self.hysteresis_ticks = 2
        # AIMD bounds per knob family
        self.shed_occupancy_floor = 0.0
        self.shed_occupancy_ceiling = 0.95
        self.shed_occupancy_step = 0.05      # additive increase
        self.md_factor = 0.5                 # multiplicative decrease
        self.window_ceiling_ms = 50.0
        self.window_growth = 1.5
        self.coalescable_gate = 0.25
        self.penalty_floor = 0.25
        self.penalty_step = 0.25             # additive recovery
        # device-duress adaptation of search_backpressure.node_duress:
        # under breaker trips / poisoned results the cpu+heap duress
        # thresholds tighten multiplicatively (duress detection fires
        # earlier while the accelerator misbehaves), recovering
        # additively toward their configured base once clean
        self.duress_threshold_floor = 0.3
        self.duress_threshold_step = 0.05    # additive recovery
        self._duress_base: Optional[dict] = None
        #: a tenant is "noisy" when its share of the window's admission
        #: attempts exceeds this multiple of its weighted fair share
        self.noisy_multiple = 2.0
        self._audit: "deque[dict]" = deque(maxlen=int(audit_capacity))
        self._lock = threading.Lock()
        self._last_tick: Optional[float] = None
        self._snap: Optional[dict] = None
        self._hot = 0
        self._healthy = 0
        self.ticks = 0
        self.adaptations = 0

    # -- settings consumers ------------------------------------------------

    def set_enabled(self, v: bool) -> None:
        self.enabled = bool(v)

    def set_interval_s(self, v: float) -> None:
        self.interval_s = max(0.01, float(v))

    # -- signal collection -------------------------------------------------

    def _signals(self) -> dict:
        """One snapshot of every measured input: the admission ledger
        (global + per-tenant), the flight recorder's breach counter,
        and the insights coalescability report."""
        from opensearch_tpu.common.telemetry import metrics
        adm = self.admission.stats()
        ins = self.insights.stats()
        return {
            "rejected": int(adm.get("rejected_count", 0)),
            "shed": int(adm.get("shed_count", 0)),
            "occupancy": float(adm.get("occupancy", 0.0)),
            "tenants": {
                label: {"admitted": int(t.get("admitted", 0)),
                        "rejected": int(t.get("rejected", 0))}
                for label, t in (adm.get("tenants") or {}).items()},
            "arrivals": int(ins.get("records", 0)),
            "coalescable_fraction": float(
                ins.get("coalescable_fraction", 0.0)),
            "captures": int(metrics().counter(
                "flight_recorder.captures").value),
            # the controller's OWN audit captures must not read back as
            # breach evidence (a self-sustaining hot loop otherwise)
            "own_captures": int(metrics().counter(
                "qos.adaptations").value),
            # accelerator duress evidence (common/device_health.py):
            # kernel-class breaker trips + sanity-guard discards
            "device_trips": int(metrics().counter(
                "device.breaker.trips").value),
            "device_poisoned": int(metrics().counter(
                "device.poisoned_results").value),
        }

    # -- pacing ------------------------------------------------------------

    def maybe_tick(self) -> None:
        """At most one evaluation per ``interval_s`` — called from the
        search dispatch edge, so an idle node adapts nothing."""
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            if (self._last_tick is not None
                    and now - self._last_tick < self.interval_s):
                return
            self._last_tick = now
        self.run_once()

    # -- the evaluation ----------------------------------------------------

    def run_once(self) -> dict:
        """One deterministic control evaluation over the counter deltas
        since the previous one.  Returns what happened (tests/logs)."""
        cur = self._signals()
        with self._lock:
            prev, self._snap = self._snap, cur
            self.ticks += 1
        if prev is None:
            # first tick only establishes the delta baseline
            return {"hot": False, "adapted": []}
        d_rej = (max(0, cur["rejected"] - prev["rejected"])
                 + max(0, cur["shed"] - prev["shed"]))
        d_arr = max(0, cur["arrivals"] - prev["arrivals"])
        d_breach = max(0, (cur["captures"] - prev["captures"])
                       - (cur["own_captures"] - prev["own_captures"]))
        # device duress: breaker trips and poisoned-result discards
        # since the previous evaluation are first-class hot evidence —
        # a misbehaving accelerator overloads the node (host fallbacks
        # burn CPU) before the admission ledger notices
        d_trips = max(0, cur["device_trips"] - prev["device_trips"])
        d_poison = max(0, (cur["device_poisoned"]
                           - prev["device_poisoned"]))
        attempts = d_arr + d_rej
        reject_rate = d_rej / attempts if attempts else 0.0
        device_hot = (d_trips + d_poison) > 0
        hot = device_hot or (attempts > 0
                             and (reject_rate >= self.high_watermark
                                  or d_breach > 0))
        healthy = (d_breach == 0 and not device_hot
                   and reject_rate <= self.low_watermark)
        with self._lock:
            self._hot = self._hot + 1 if hot else 0
            self._healthy = self._healthy + 1 if healthy else 0
            act_hot = self._hot >= self.hysteresis_ticks
            act_healthy = (not act_hot
                           and self._healthy >= self.hysteresis_ticks)
            if act_hot:
                self._hot = 0
            if act_healthy:
                self._healthy = 0
        evidence = {
            "reject_rate": round(reject_rate, 4),
            "rejected": d_rej, "attempts": attempts,
            "breaches": d_breach,
            "occupancy": cur["occupancy"],
            "coalescable_fraction": cur["coalescable_fraction"],
            "device_trips": d_trips,
            "poisoned_results": d_poison,
        }
        adapted: list[dict] = []
        if act_hot:
            adapted += self._tighten(cur, prev, evidence)
        elif act_healthy:
            adapted += self._relax(evidence)
        return {"hot": hot, "adapted": adapted}

    # -- multiplicative decrease (pressure) --------------------------------

    def _tighten(self, cur: dict, prev: dict,
                 evidence: dict) -> list[dict]:
        from opensearch_tpu.cluster import response_collector as rc_mod
        from opensearch_tpu.search import engine as engine_mod
        adapted = []
        # 1) shed earlier: duress sheds fire at lower occupancy
        old = rc_mod.SHED_OCCUPANCY
        new = max(self.shed_occupancy_floor,
                  round(old * self.md_factor, 4))
        if new != old:
            rc_mod.SHED_OCCUPANCY = new
            adapted.append(self._record("shed_occupancy", old, new,
                                        evidence))
        # 2) coalesce harder: a measurably coalescable workload under
        # pressure amortizes better with a wider batch window (only the
        # AUTO window — an operator-pinned window_ms stays pinned)
        if (cur["coalescable_fraction"] >= self.coalescable_gate
                and engine_mod.BATCHER_WINDOW_MS == 0):
            old_w = float(engine_mod.AUTO_WINDOW_MS)
            new_w = min(self.window_ceiling_ms,
                        round(max(old_w, 1.0) * self.window_growth, 3))
            if new_w != old_w:
                engine_mod.AUTO_WINDOW_MS = new_w
                adapted.append(self._record(
                    "batcher_auto_window_ms", old_w, new_w, evidence))
        # 3) squeeze the noisy neighbor: the tenant dominating this
        # window's admission attempts far beyond its weighted fair
        # share loses carved share (floor: one permit — isolation,
        # never starvation)
        noisy = self._noisy_tenant(cur, prev)
        if noisy is not None:
            label, share, fair = noisy
            old_p = float(self.admission.tenant_penalty.get(label, 1.0))
            new_p = max(self.penalty_floor,
                        round(old_p * self.md_factor, 4))
            if new_p != old_p:
                self.admission.set_tenant_penalty(label, new_p)
                adapted.append(self._record(
                    "tenant_penalty", old_p, new_p,
                    dict(evidence, attempt_share=round(share, 4),
                         fair_share=round(fair, 4)),
                    tenant=label))
        # 4) device duress tightens the node_duress thresholds
        # themselves: while the accelerator trips breakers / returns
        # poison, every search it degrades burns host CPU — lowering
        # the cpu/heap duress thresholds makes the C3 selector derank
        # and the coordinator shed THIS node's copies earlier (the
        # audit record carries the trip/poison counts as evidence)
        if (self.backpressure is not None
                and (evidence.get("device_trips", 0)
                     + evidence.get("poisoned_results", 0)) > 0):
            adapted += self._tighten_duress_thresholds(evidence)
        return adapted

    def _duress_trackers(self) -> dict:
        return {"cpu_threshold":
                self.backpressure.trackers["cpu_usage"],
                "heap_threshold":
                self.backpressure.trackers["heap_usage"]}

    def _tighten_duress_thresholds(self, evidence: dict) -> list[dict]:
        adapted = []
        trackers = self._duress_trackers()
        if self._duress_base is None:
            # the configured values are the recovery ceiling
            self._duress_base = {k: float(t.threshold)
                                 for k, t in trackers.items()}
        for name, tracker in sorted(trackers.items()):
            old = float(tracker.threshold)
            new = max(self.duress_threshold_floor,
                      round(old * self.md_factor, 4))
            if new != old:
                tracker.threshold = new
                adapted.append(self._record(
                    f"node_duress.{name}", old, new, evidence))
        return adapted

    def _relax_duress_thresholds(self, evidence: dict) -> list[dict]:
        if self.backpressure is None or self._duress_base is None:
            return []
        adapted = []
        for name, tracker in sorted(self._duress_trackers().items()):
            base = self._duress_base.get(name)
            old = float(tracker.threshold)
            if base is None or old >= base:
                continue
            new = min(base, round(old + self.duress_threshold_step, 4))
            tracker.threshold = new
            adapted.append(self._record(
                f"node_duress.{name}", old, new, evidence))
        return adapted

    def _noisy_tenant(self, cur: dict, prev: dict):
        """(label, attempt_share, fair_share) of the dominant tenant
        when it exceeds ``noisy_multiple`` x its weighted fair share —
        and at least one OTHER tenant is known to the gate (with a
        single tenant there is no neighbor to protect)."""
        shares = dict(getattr(self.admission, "tenant_shares", {}) or {})
        deltas = {}
        for label, t in cur["tenants"].items():
            p = prev["tenants"].get(label, {})
            d = (max(0, t["admitted"] - int(p.get("admitted", 0)))
                 + max(0, t["rejected"] - int(p.get("rejected", 0))))
            if d > 0:
                deltas[label] = d
        if not deltas or len(cur["tenants"]) < 2:
            return None         # no victim in evidence: nothing to weigh
        total = sum(deltas.values())
        default_share = float(getattr(self.admission, "default_share",
                                      1.0))
        weight_total = sum(shares.values()) + default_share
        label = max(sorted(deltas), key=lambda t: deltas[t])
        share = deltas[label] / total
        fair = (shares.get(label, default_share) / weight_total
                if weight_total > 0 else 1.0)
        if share > self.noisy_multiple * fair:
            return label, share, fair
        return None

    # -- additive increase (recovery) --------------------------------------

    def _relax(self, evidence: dict) -> list[dict]:
        from opensearch_tpu.cluster import response_collector as rc_mod
        from opensearch_tpu.search import engine as engine_mod
        adapted = []
        old = rc_mod.SHED_OCCUPANCY
        if 0 < old < self.shed_occupancy_ceiling:
            new = min(self.shed_occupancy_ceiling,
                      round(old + self.shed_occupancy_step, 4))
            rc_mod.SHED_OCCUPANCY = new
            adapted.append(self._record("shed_occupancy", old, new,
                                        evidence))
        base = float(self.insights.coalesce_window_ms)
        old_w = float(engine_mod.AUTO_WINDOW_MS)
        if engine_mod.BATCHER_WINDOW_MS == 0 and old_w > base:
            new_w = max(base, round(old_w * self.md_factor, 3))
            engine_mod.AUTO_WINDOW_MS = new_w
            adapted.append(self._record(
                "batcher_auto_window_ms", old_w, new_w, evidence))
        for label in sorted(dict(self.admission.tenant_penalty)):
            old_p = float(self.admission.tenant_penalty[label])
            new_p = min(1.0, round(old_p + self.penalty_step, 4))
            self.admission.set_tenant_penalty(label, new_p)
            adapted.append(self._record("tenant_penalty", old_p, new_p,
                                        evidence, tenant=label))
        adapted += self._relax_duress_thresholds(evidence)
        return adapted

    # -- audit ring --------------------------------------------------------

    def _record(self, knob: str, old, new, evidence: dict,
                tenant: Optional[str] = None) -> dict:
        from opensearch_tpu.common.telemetry import flight_recorder, \
            metrics
        rec = {"tick": self.ticks, "knob": knob, "old": old, "new": new,
               "evidence": dict(evidence)}
        if tenant is not None:
            rec["tenant"] = tenant
        with self._lock:
            self._audit.append(rec)
            self.adaptations += 1
        metrics().counter("qos.adaptations").inc()
        flight_recorder().record(
            "qos_adaptation",
            f"qos: [{knob}] {old} -> {new}"
            + (f" tenant [{tenant}]" if tenant else ""),
            detail=dict(rec))
        return rec

    def record_adaptation(self, knob: str, old, new, evidence: dict,
                          tenant: Optional[str] = None) -> dict:
        """Public audit-ring append for external controllers that act
        on QoS evidence (the searcher autoscaler): same record shape,
        same ring, same flight-recorder capture — one audit surface for
        every adaptive decision in the system."""
        return self._record(knob, old, new, evidence, tenant=tenant)

    def audit(self, limit: int = 64) -> list[dict]:
        """Most recent adaptation records, newest first."""
        with self._lock:
            out = [dict(r) for r in self._audit]
        out.reverse()
        return out[: max(0, int(limit))]

    def stats(self) -> dict:
        from opensearch_tpu.cluster import response_collector as rc_mod
        from opensearch_tpu.search import engine as engine_mod
        with self._lock:
            hot, healthy = self._hot, self._healthy
            ticks, adaptations = self.ticks, self.adaptations
        return {
            "enabled": self.enabled,
            "interval_s": self.interval_s,
            "ticks": ticks,
            "adaptations": adaptations,
            "hot_streak": hot,
            "healthy_streak": healthy,
            "knobs": {
                "shed_occupancy": rc_mod.SHED_OCCUPANCY,
                "batcher_auto_window_ms": engine_mod.AUTO_WINDOW_MS,
                "tenant_penalties":
                    dict(self.admission.tenant_penalty),
                **({"node_duress": {
                    name: float(t.threshold)
                    for name, t in sorted(
                        self._duress_trackers().items())}}
                   if self.backpressure is not None else {}),
            },
            "audit": self.audit(16),
        }


def check_tenant_attribution(admission_tenants: dict,
                             insights_tenants,
                             client_ledger: dict) -> dict:
    """Cross-check the node's per-tenant accounting against an external
    client's own outcome ledger (the open-loop load harness,
    ``testing/loadgen.py``).  For every search-path tenant the client
    drove, three invariants must hold:

    - every 2xx search held an admission permit, so the admission
      block's ``admitted`` must cover the client's served count;
    - every admission ``rejected`` surfaced to a client as a 429, so
      the node may not claim more rejections than clients observed;
    - every served search landed in insights, so the tenant's insights
      rollup ``count`` must cover the client's served count (skipped
      when ``insights_tenants`` is None — e.g. insights disabled).

    Returns ``{tenant: [discrepancy strings]}`` — empty lists mean the
    tenant's books balance; the harness turns each entry into an
    ``attribution.<tenant>`` verdict.
    """
    problems: dict = {}
    for tenant, led in sorted(client_ledger.items()):
        probs: list = []
        if led.get("searchish", True):
            adm = admission_tenants.get(tenant)
            served = int(led.get("ok", 0))
            seen_429 = int(led.get("status_429", 0))
            if adm is None:
                if served or seen_429:
                    probs.append("tenant missing from admission stats")
            else:
                admitted = int(adm.get("admitted", 0))
                rejected = int(adm.get("rejected", 0)) + int(
                    adm.get("shed", 0))
                if admitted < served:
                    probs.append(
                        f"admission admitted {admitted} < client "
                        f"served {served}")
                if rejected > seen_429:
                    probs.append(
                        f"admission rejected+shed {rejected} > client "
                        f"429s {seen_429}")
            if insights_tenants is not None:
                roll = insights_tenants.get(tenant) or {}
                count = int(roll.get("count", 0))
                if count < served:
                    probs.append(
                        f"insights count {count} < client served "
                        f"{served}")
        problems[tenant] = probs
    return problems
