"""Fetch phase: build hit objects from winning doc ids.

Analog of ``search/fetch/FetchPhase.java`` and the ``FetchSourcePhase``
sub-phase (source include/exclude filtering with wildcard patterns)."""

from __future__ import annotations

import fnmatch
from typing import Any, Optional, Union


def _match_any(path: str, patterns: list[str]) -> bool:
    for p in patterns:
        if fnmatch.fnmatchcase(path, p) or path.startswith(p + "."):
            return True
        # a pattern deeper than the path keeps the ancestor object
        if p.startswith(path + "."):
            return True
    return False


def _filter_tree(obj: Any, prefix: str, includes: Optional[list[str]],
                 excludes: list[str]):
    if not isinstance(obj, dict):
        return obj
    out = {}
    for k, v in obj.items():
        path = f"{prefix}{k}"
        if excludes and any(fnmatch.fnmatchcase(path, p)
                            or path.startswith(p + ".") for p in excludes):
            continue
        if includes is not None and not _match_any(path, includes):
            continue
        if isinstance(v, dict):
            sub_includes = includes
            if includes is not None and any(
                    fnmatch.fnmatchcase(path, p) or path.startswith(p + ".")
                    for p in includes):
                sub_includes = None  # whole subtree included
            v = _filter_tree(v, path + ".", sub_includes, excludes)
        out[k] = v
    return out


def filter_source(source: dict, spec: Union[bool, str, list, dict, None]):
    """Apply a ``_source`` request option.  Returns None when `_source`
    is disabled for the response."""
    if spec is None or spec is True:
        return source
    if spec is False:
        return None
    if isinstance(spec, str):
        spec = [spec]
    if isinstance(spec, list):
        return _filter_tree(source, "", [str(s) for s in spec], [])
    includes = spec.get("includes") or spec.get("include")
    excludes = spec.get("excludes") or spec.get("exclude") or []
    if isinstance(includes, str):
        includes = [includes]
    if isinstance(excludes, str):
        excludes = [excludes]
    return _filter_tree(source, "",
                        None if not includes else list(includes),
                        list(excludes))


# ---------------------------------------------------------------------------
# Fetch sub-phases: highlight / explain / docvalue_fields / fields
# (ref search/fetch/FetchPhase.java + search/fetch/subphase/)
# ---------------------------------------------------------------------------


def collect_query_terms(q, mapper) -> dict:
    """Walk the parsed query tree collecting the analyzed terms (and
    wildcard/prefix patterns) per field — what the highlighter marks
    (HighlightPhase's extracted-terms step)."""
    from opensearch_tpu.search import query_dsl as dsl

    out: dict[str, dict] = {}

    def bucket(field):
        return out.setdefault(field, {"terms": set(), "patterns": []})

    def walk(node):
        if node is None:
            return
        if isinstance(node, (dsl.MatchQuery, dsl.MatchPhraseQuery)):
            ft = mapper.field_type(node.field)
            if ft is not None and hasattr(ft, "search_terms"):
                bucket(node.field)["terms"].update(
                    ft.search_terms(str(node.query), mapper.analyzers))
            else:
                bucket(node.field)["terms"].add(str(node.query))
        elif isinstance(node, dsl.TermQuery):
            bucket(node.field)["terms"].add(str(node.value).lower())
        elif isinstance(node, dsl.TermsQuery):
            bucket(node.field)["terms"].update(
                str(v).lower() for v in node.values)
        elif isinstance(node, (dsl.PrefixQuery,)):
            bucket(node.field)["patterns"].append(
                str(node.value).lower() + "*")
        elif isinstance(node, dsl.WildcardQuery):
            bucket(node.field)["patterns"].append(str(node.value).lower())
        elif isinstance(node, dsl.FuzzyQuery):
            bucket(node.field)["terms"].add(str(node.value).lower())
        elif isinstance(node, dsl.MultiMatchQuery):
            for field, _b in node.fields:
                ft = mapper.field_type(field)
                if ft is not None and hasattr(ft, "search_terms"):
                    bucket(field)["terms"].update(
                        ft.search_terms(str(node.query), mapper.analyzers))
        elif isinstance(node, dsl.BoolQuery):
            for c in (*node.must, *node.should, *node.filter):
                walk(c)                    # must_not terms don't highlight
        elif isinstance(node, dsl.DisMaxQuery):
            for c in node.queries:
                walk(c)
        elif isinstance(node, dsl.ConstantScoreQuery):
            walk(node.query)
        elif isinstance(node, dsl.BoostingQuery):
            walk(node.positive)
        elif isinstance(node, (dsl.ScriptScoreQuery,
                               dsl.FunctionScoreQuery)):
            walk(node.query)
        elif isinstance(node, dsl.HybridQuery):
            for c in node.queries:
                walk(c)
    walk(q)
    return out


def _fragment_spans(marks: list, text_len: int, fragment_size: int,
                    n_fragments: int) -> list:
    """Greedy fragmenter: one window per run of nearby matches."""
    spans = []
    for start, end in marks:
        if spans and start - spans[-1][1] <= fragment_size // 2:
            spans[-1][1] = end
        else:
            spans.append([start, end])
        if len(spans) > n_fragments * 4:
            break
    out = []
    for start, end in spans[: n_fragments]:
        pad = max((fragment_size - (end - start)) // 2, 0)
        lo = max(0, start - pad)
        hi = min(text_len, end + pad)
        out.append((lo, hi))
    return out


def highlight_field(text: str, ft, mapper, terms: set, patterns: list,
                    spec: dict) -> list:
    """Plain-highlighter analog: analyze the stored text (tokens carry
    offsets), mark tokens whose analyzed term matches, emit tagged
    fragments."""
    import fnmatch as _fn

    analyzer = mapper.analyzers.get(
        getattr(ft, "analyzer_name", "standard"))
    pre = (spec.get("pre_tags") or ["<em>"])[0]
    post = (spec.get("post_tags") or ["</em>"])[0]
    fragment_size = int(spec.get("fragment_size", 100))
    n_fragments = int(spec.get("number_of_fragments", 5))
    marks = []
    for tok in analyzer.analyze(text):
        hit = tok.term in terms or any(
            _fn.fnmatchcase(tok.term, p) for p in patterns)
        if hit:
            marks.append((tok.start_offset, tok.end_offset))
    if not marks:
        return []
    if n_fragments == 0:                   # whole-field highlighting
        spans = [(0, len(text))]
    else:
        spans = _fragment_spans(marks, len(text), fragment_size,
                                n_fragments)
    frags = []
    for lo, hi in spans:
        inside = [(s, e) for s, e in marks if s >= lo and e <= hi]
        buf = []
        pos = lo
        for s, e in inside:
            buf.append(text[pos:s])
            buf.append(pre + text[s:e] + post)
            pos = e
        buf.append(text[pos:hi])
        frags.append("".join(buf))
    return frags


def run_highlight(body_highlight: dict, source: dict, query, mapper):
    """The per-hit highlight sub-phase; returns {field: [fragments]}."""
    per_field = collect_query_terms(query, mapper)
    global_spec = {k: v for k, v in body_highlight.items()
                   if k != "fields"}
    out = {}
    fields_spec = body_highlight.get("fields") or {}
    if isinstance(fields_spec, list):      # accept the array form
        merged = {}
        for entry in fields_spec:
            merged.update(entry)
        fields_spec = merged
    for field, spec in fields_spec.items():
        spec = {**global_spec, **(spec or {})}
        ft = mapper.field_type(field)
        info = per_field.get(field)
        require_match = spec.get("require_field_match", True)
        if info is None and require_match:
            continue
        if info is None:
            # require_field_match:false highlights with terms from ANY
            # field in the query
            info = {"terms": set(), "patterns": []}
            for other in per_field.values():
                info["terms"] |= other["terms"]
                info["patterns"] += other["patterns"]
        value = source.get(field)
        if value is None:
            continue
        values = value if isinstance(value, list) else [value]
        frags = []
        for v in values:
            frags.extend(highlight_field(str(v), ft, mapper,
                                         info["terms"],
                                         info["patterns"], spec))
        if frags:
            out[field] = frags
    return out


def docvalue_fields(specs: list, seg, local: int, mapper) -> dict:
    """Per-hit doc-values read straight from the columns
    (DocValueFieldsPhase)."""
    from opensearch_tpu.mapping.types import format_date_millis

    out = {}
    for spec in specs or []:
        if isinstance(spec, dict):
            field = spec.get("field")
            fmt = spec.get("format")
        else:
            field, fmt = str(spec), None
        ft = mapper.field_type(field)
        if ft is None:
            continue
        vals = []
        ndv = seg.numeric_dv.get(field)
        odv = seg.ordinal_dv.get(field)
        if ndv is not None and len(ndv.value_docs):
            import numpy as np
            sel = ndv.values[ndv.value_docs == local]
            for v in sel.tolist():
                if ft.type_name == "date" and fmt != "epoch_millis":
                    vals.append(format_date_millis(int(v)))
                elif ft.dv_kind == "long":
                    vals.append(int(v))
                else:
                    vals.append(float(v))
        elif odv is not None and len(odv.value_docs):
            sel = odv.ords[odv.value_docs == local]
            vals = [odv.ord_terms[int(o)] for o in sel.tolist()]
        if vals:
            out[field] = vals
    return out


def fields_option(specs: list, source: dict) -> dict:
    """The modern ``fields`` API: flattened leaf values (arrays) matched
    by name or wildcard from the source (FieldFetchPhase analog)."""
    import fnmatch as _fn

    flat: dict[str, list] = {}

    def walk(obj, path):
        if isinstance(obj, dict):
            for k, v in obj.items():
                walk(v, f"{path}.{k}" if path else k)
        elif isinstance(obj, list):
            for v in obj:
                walk(v, path)
        else:
            flat.setdefault(path, []).append(obj)

    walk(source, "")
    out = {}
    for spec in specs or []:
        pattern = spec.get("field") if isinstance(spec, dict) else str(spec)
        if not pattern:
            continue                   # malformed entry: no field named
        for path, vals in flat.items():
            if _fn.fnmatchcase(path, pattern):
                out.setdefault(path, []).extend(vals)
    return out


def explain_hit(score, query, seg, local: int, ctx) -> dict:
    """Per-hit score explanation (ExplainPhase).  Term-bag queries get a
    real BM25 breakdown recomputed host-side from the postings; other
    query shapes get a one-level summary (value + query description)."""
    import math

    from opensearch_tpu.search import query_dsl as dsl

    def bm25_details(field, terms, boost):
        pf = seg.postings.get(field)
        details = []
        if pf is None:
            return details
        stats = ctx.field_stats(field)
        n_docs = max(stats.doc_count, 1)
        avgdl = stats.avgdl
        dl = float(pf.doc_lens[local]) if local < len(pf.doc_lens) else 0.0
        for t in terms:
            tid = pf.term_id(t)
            if tid < 0:
                continue
            lo, hi = int(pf.offsets[tid]), int(pf.offsets[tid + 1])
            entry = None
            import numpy as np
            rows = pf.doc_ids[lo:hi]
            idx = np.searchsorted(rows, local)
            if idx < len(rows) and rows[idx] == local:
                entry = float(pf.tfs[lo + idx])
            if entry is None:
                continue
            df = ctx.df(field, t)
            idf = math.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))
            k1, b = 1.2, 0.75
            norm = k1 * (1.0 - b + b * dl / avgdl)
            contrib = boost * idf * entry / (entry + norm)
            details.append({
                "value": contrib,
                "description": f"weight({field}:{t} in {local})",
                "details": [
                    {"value": boost, "description": "boost", "details": []},
                    {"value": idf,
                     "description": f"idf, n={df}, N={n_docs}",
                     "details": []},
                    {"value": entry / (entry + norm),
                     "description": f"tf, freq={entry}, dl={dl}, "
                                    f"avgdl={avgdl:.2f}", "details": []},
                ]})
        return details

    details = []
    if isinstance(query, dsl.MatchQuery):
        ft = ctx.field_type(query.field)
        terms = (ft.search_terms(str(query.query), ctx.mapper.analyzers)
                 if ft is not None and hasattr(ft, "search_terms")
                 else [str(query.query)])
        details = bm25_details(query.field, terms, query.boost)
    elif isinstance(query, dsl.TermQuery):
        details = bm25_details(query.field, [str(query.value).lower()],
                               query.boost)
    elif isinstance(query, dsl.BoolQuery):
        for c in (*query.must, *query.should):
            sub = explain_hit(None, c, seg, local, ctx)
            if sub["details"] or sub["value"] is not None:
                details.append(sub)
    value = score if score is not None else sum(
        d["value"] for d in details if d.get("value") is not None)
    return {"value": value,
            "description": f"{type(query).__name__}, sum of:",
            "details": details}
