"""Fetch phase: build hit objects from winning doc ids.

Analog of ``search/fetch/FetchPhase.java`` and the ``FetchSourcePhase``
sub-phase (source include/exclude filtering with wildcard patterns)."""

from __future__ import annotations

import fnmatch
from typing import Any, Optional, Union


def _match_any(path: str, patterns: list[str]) -> bool:
    for p in patterns:
        if fnmatch.fnmatchcase(path, p) or path.startswith(p + "."):
            return True
        # a pattern deeper than the path keeps the ancestor object
        if p.startswith(path + "."):
            return True
    return False


def _filter_tree(obj: Any, prefix: str, includes: Optional[list[str]],
                 excludes: list[str]):
    if not isinstance(obj, dict):
        return obj
    out = {}
    for k, v in obj.items():
        path = f"{prefix}{k}"
        if excludes and any(fnmatch.fnmatchcase(path, p)
                            or path.startswith(p + ".") for p in excludes):
            continue
        if includes is not None and not _match_any(path, includes):
            continue
        if isinstance(v, dict):
            sub_includes = includes
            if includes is not None and any(
                    fnmatch.fnmatchcase(path, p) or path.startswith(p + ".")
                    for p in includes):
                sub_includes = None  # whole subtree included
            v = _filter_tree(v, path + ".", sub_includes, excludes)
        out[k] = v
    return out


def filter_source(source: dict, spec: Union[bool, str, list, dict, None]):
    """Apply a ``_source`` request option.  Returns None when `_source`
    is disabled for the response."""
    if spec is None or spec is True:
        return source
    if spec is False:
        return None
    if isinstance(spec, str):
        spec = [spec]
    if isinstance(spec, list):
        return _filter_tree(source, "", [str(s) for s in spec], [])
    includes = spec.get("includes") or spec.get("include")
    excludes = spec.get("excludes") or spec.get("exclude") or []
    if isinstance(includes, str):
        includes = [includes]
    if isinstance(excludes, str):
        excludes = [excludes]
    return _filter_tree(source, "",
                        None if not includes else list(includes),
                        list(excludes))
