"""Reader contexts: scroll cursors, points-in-time, sliced scans.

Analog of the reference's server-held reader leases (ref
search/internal/PitReaderContext.java, SearchService.java:170,185
keepalive machinery, search/slice/SliceBuilder.java:81).  A context pins
a ``ShardSearcher`` — which is already a point-in-time snapshot (its
``ShardContext`` captured the live bitmaps at acquire; segments are
immutable) — so deletes/refreshes after creation never change what the
context sees, exactly like a held Lucene reader.

- **Scroll**: the full sorted match list is materialized once on
  creation and paged by cursor.  Memory is O(matched docs) per scroll,
  the same trade the reference's scroll contexts make (they hold
  per-shard ScoreDocs + reader leases); keepalive bounds the damage.
- **PIT**: pins only the searcher; each page re-runs the query against
  the frozen snapshot with ``search_after`` pagination.
- **Slice**: ``{"id": i, "max": n}`` partitions the doc space by a hash
  of (segment, local doc) — n independent cursors over disjoint doc
  sets whose union is exactly the full set (the reference's sliced
  scroll/PIT for parallel export).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Optional

from opensearch_tpu.common.errors import (IllegalArgumentError,
                                          OpenSearchTpuError)


class SearchContextMissingError(OpenSearchTpuError):
    status = 404


def parse_keepalive(value, default_ms: int = 60_000) -> int:
    if value is None:
        return default_ms
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value)
    units = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
             "d": 86_400_000}
    try:
        for suffix, mult in sorted(units.items(),
                                   key=lambda kv: -len(kv[0])):
            if s.endswith(suffix):
                return int(float(s[: -len(suffix)]) * mult)
        return int(float(s) * 1000)
    except ValueError:
        raise IllegalArgumentError(
            f"failed to parse keep-alive [{s}]") from None


def slice_filter(slice_spec: Optional[dict]):
    """Row predicate for ``{"id": i, "max": n}`` — deterministic disjoint
    partition of (seg, local) pairs (SliceBuilder's doc-hash strategy)."""
    if slice_spec is None:
        return None
    sid = int(slice_spec.get("id", 0))
    smax = int(slice_spec.get("max", 1))
    if smax < 2:
        raise IllegalArgumentError("[slice] max must be >= 2")
    if not (0 <= sid < smax):
        raise IllegalArgumentError(
            f"slice id [{sid}] must be in [0, {smax})")

    def pred(seg_i: int, local: int) -> bool:
        return (seg_i * 2654435761 + local) % smax == sid
    return pred


class ScrollContext:
    _ROW_BYTES = 96         # dict + three boxed values, rough host cost

    def __init__(self, searcher, rows: list, total: int, page_size: int,
                 source_spec, index_name: str):
        from opensearch_tpu.common.breakers import breaker_service
        self.searcher = searcher
        self.rows = rows
        self.total = total
        self.page_size = page_size
        self.source_spec = source_spec
        self.index_name = index_name
        self.pos = 0
        # the materialized cursor is the scroll's memory cost — charged
        # to the request breaker until the context closes/expires
        self._breaker = breaker_service().request
        self._reserved = len(rows) * self._ROW_BYTES
        self._breaker.add_estimate(self._reserved, label="scroll context")

    def next_page(self) -> list:
        page = self.rows[self.pos: self.pos + self.page_size]
        self.pos += len(page)
        return page

    def release(self):
        self._breaker.release(self._reserved)
        self._reserved = 0


class PitContext:
    def __init__(self, searcher, index_name: str):
        self.searcher = searcher
        self.index_name = index_name


class ReaderContextRegistry:
    """Keepalive-bounded registry of scroll/PIT contexts.  ``now_fn`` is
    injectable so tests drive expiry deterministically."""

    def __init__(self, now_fn: Callable[[], float] = time.monotonic,
                 max_open: int = 500):
        self._now = now_fn
        self._max_open = max_open
        self._lock = threading.Lock()
        self._ctxs: dict[str, tuple[object, float, int]] = {}
        # id -> (ctx, expires_at_monotonic_ms, keepalive_ms)

    @staticmethod
    def _release(ctx):
        rel = getattr(ctx, "release", None)
        if rel is not None:
            rel()

    def _reap(self):
        now = self._now() * 1000
        for cid in [c for c, (_ctx, exp, _ka) in self._ctxs.items()
                    if exp <= now]:
            self._release(self._ctxs.pop(cid)[0])

    # search.max_keep_alive (dynamic; node wires the consumer)
    max_keep_alive_s = 24 * 3600.0

    # search.default_keep_alive (dynamic; node wires the consumer):
    # the keepalive a PIT opened without an explicit keep_alive gets
    default_keep_alive_s = 300.0

    def _check_keepalive(self, keepalive_ms: int):
        limit_ms = int(self.max_keep_alive_s * 1000)
        if keepalive_ms > limit_ms:
            raise IllegalArgumentError(
                f"Keep alive for request ({keepalive_ms}ms) is too "
                f"large. It must be less than ({limit_ms}ms). This "
                "limit can be set by changing the [search.max_keep_"
                "alive] cluster level setting.")

    def open(self, ctx, keepalive_ms: int) -> str:
        self._check_keepalive(keepalive_ms)
        with self._lock:
            self._reap()
            if len(self._ctxs) >= self._max_open:
                raise IllegalArgumentError(
                    f"trying to open too many search contexts "
                    f"(>{self._max_open}) — close scrolls/PITs or let "
                    "keepalives lapse")
            cid = uuid.uuid4().hex
            self._ctxs[cid] = (ctx, self._now() * 1000 + keepalive_ms,
                              keepalive_ms)
            return cid

    def get(self, cid: str, keepalive_ms: Optional[int] = None):
        """Fetch + touch (every access extends the lease, like the
        reference's keepalive refresh on use)."""
        with self._lock:
            self._reap()
            entry = self._ctxs.get(cid)
            if entry is None:
                raise SearchContextMissingError(
                    f"No search context found for id [{cid}]")
            ctx, _exp, ka = entry
            if keepalive_ms is not None:
                self._check_keepalive(keepalive_ms)
                ka = keepalive_ms
            self._ctxs[cid] = (ctx, self._now() * 1000 + ka, ka)
            return ctx

    def close(self, cid: str) -> bool:
        with self._lock:
            entry = self._ctxs.pop(cid, None)
            if entry is not None:
                self._release(entry[0])
            return entry is not None

    def close_all(self) -> int:
        with self._lock:
            n = len(self._ctxs)
            for ctx, _exp, _ka in self._ctxs.values():
                self._release(ctx)
            self._ctxs.clear()
            return n

    def count(self) -> int:
        with self._lock:
            self._reap()
            return len(self._ctxs)
