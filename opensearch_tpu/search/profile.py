"""Per-query phase-attributed profiler (the real Profile API).

Analog of the reference's ``search/profile/query/QueryProfiler`` +
``Profilers`` tree, reshaped for this engine's execution model: Lucene
profiles per-collector callbacks (``next_doc``/``score`` per leaf), but
here a segment is ONE fused XLA program — the observable phases are the
host-side stages around those programs:

    rewrite     query-DSL parse (QueryBuilder.rewrite analog)
    plan_cache  canonicalization + compiled-plan cache lookup
    compile     plan-tree construction (toQuery/Weight build analog)
    prepare     per-(plan, segment) bindings staging (incl. H2D)
    can_match   can-match + block-max pruning decisions per segment
    dispatch    device program launches / host fast-path scoring
    reduce      host sync + cross-segment top-k merge (collector analog)
    fetch       source materialization, highlight, docvalues

plus *engine attribution* only this stack can report: plan-cache and
prepared-bindings hit/miss, segments pruned vs scanned (and why),
XLA retrace/compile events, host-vs-device execution path, and msearch
batch-coalescing group membership.

Zero-cost contract: a ``QueryProfiler`` exists only when the request
carried ``profile: true`` — every instrumentation point in the engine is
guarded by ``prof is not None`` at plan/segment granularity (never
per-posting), and profiled execution takes the *same* code path, so hits
are byte-identical with and without profiling (pinned in
tests/test_profile.py).
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

# response-breakdown phase keys, in pipeline order.  ``queue`` is the
# continuous batcher's wait window (search/engine.py): time a member
# spent parked before its group's shared dispatch — it precedes every
# execution phase and is never counted as query work.
PHASES = ("queue", "rewrite", "plan_cache", "compile", "prepare",
          "can_match", "dispatch", "reduce", "fetch")

# phases counted into the query section's time_in_nanos (the collector
# section owns "reduce", the fetch phase is its own response field in
# the reference too — no double-stamping)
_QUERY_PHASES = ("rewrite", "plan_cache", "compile", "prepare",
                 "can_match", "dispatch")

# keep the per-segment decision list bounded — a pathological segment
# count must not balloon the response
_MAX_SEGMENT_RECORDS = 256


def xla_program_count() -> int:
    """Live compiled-program count across the query-path jit entry
    points — a growing count across identical queries means the hot
    path is retracing (the attribution bench.py tracks per phase).

    Delegates to the per-kernel compile registry
    (``common/device_ledger.kernel_registry``), whose version-tolerant
    ``_cache_size`` shim degrades a removed jit introspection to a
    counted ``unavailable`` instead of breaking the profiler."""
    from opensearch_tpu.common.device_ledger import kernel_registry
    return kernel_registry().program_count()


class QueryProfiler:
    """Accumulates monotonic-clock phase timings + engine attribution
    for ONE query execution (or one msearch batch group — members of a
    coalesced group share the group's timings by construction)."""

    __slots__ = ("phases", "counts", "attrs", "segments", "_xla0")

    def __init__(self):
        self.phases: dict[str, float] = {}       # name -> seconds
        self.counts: dict[str, int] = {}
        self.attrs: dict = {}
        self.segments: list[dict] = []
        self._xla0 = xla_program_count()

    # -- timing ------------------------------------------------------------

    def add(self, phase: str, seconds: float, n: int = 1) -> None:
        self.phases[phase] = self.phases.get(phase, 0.0) + seconds
        self.counts[phase] = self.counts.get(phase, 0) + n

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add(name, time.monotonic() - t0)

    # -- attribution -------------------------------------------------------

    def set(self, key: str, value) -> None:
        self.attrs[key] = value

    def inc(self, key: str, n: int = 1) -> None:
        self.attrs[key] = self.attrs.get(key, 0) + n

    # -- per-segment decisions ---------------------------------------------

    def seg_scanned(self, seg_id: str, seconds: float) -> None:
        """A segment that actually dispatched (device program launched
        or host fast path scored)."""
        self.add("dispatch", seconds)
        self._seg(seg_id, "scanned", seconds)

    def seg_pruned(self, seg_id: str, reason: str,
                   seconds: float) -> None:
        """A segment skipped without dispatch: ``pruned_can_match`` /
        ``pruned_min_score`` / ``pruned_kth`` — the decision cost lands
        in the can_match phase."""
        self.add("can_match", seconds)
        self._seg(seg_id, reason, seconds)

    def _seg(self, seg_id: str, decision: str, seconds: float) -> None:
        if len(self.segments) < _MAX_SEGMENT_RECORDS:
            self.segments.append({"segment": seg_id,
                                  "decision": decision,
                                  "time_in_nanos": int(seconds * 1e9)})

    def segment_summary(self, total: int) -> dict:
        counts = {"total": int(total), "scanned": 0,
                  "pruned_can_match": 0, "pruned_min_score": 0,
                  "pruned_kth": 0}
        for rec in self.segments:
            d = rec["decision"]
            counts[d] = counts.get(d, 0) + 1
        reached = sum(v for k, v in counts.items() if k != "total")
        # deadline/cancellation can stop the scan early: the remainder
        # is reported, so scanned + pruned + not_reached == total
        counts["not_reached"] = max(0, int(total) - reached)
        return counts

    # -- rendering ---------------------------------------------------------

    def breakdown(self) -> dict:
        out = {}
        for name in PHASES:
            out[name] = int(self.phases.get(name, 0.0) * 1e9)
            out[f"{name}_count"] = self.counts.get(name, 0)
        return out

    def shard_section(self, index_name: str, shard_id, *,
                      plan_type: str, description: str,
                      total_segments: int,
                      query_json: Optional[dict] = None) -> dict:
        """One ``profile.shards[]`` element in the OpenSearch response
        shape (``shards[].searches[].query[].breakdown`` +
        ``rewrite_time`` + ``collector``), extended with the ``engine``
        attribution block and the per-segment decision list."""
        bd = self.breakdown()
        query_ns = sum(bd[p] for p in _QUERY_PHASES)
        engine = dict(self.attrs)
        engine.setdefault("plan_cache", "miss")
        engine.setdefault("execution_path", "device")
        # profile responses are never served from or stored into the
        # request cache (indices/service.py admission policy) — the
        # attribution states the policy instead of a meaningless miss
        engine.setdefault("request_cache", "bypass")
        engine["xla_compiles"] = max(
            0, xla_program_count() - self._xla0)
        engine["segments"] = self.segment_summary(total_segments)
        section = {
            "id": f"[{index_name}][{shard_id}]",
            "searches": [{
                "query": [{
                    "type": plan_type,
                    "description": description[:200],
                    "time_in_nanos": query_ns,
                    "breakdown": bd,
                    "children": [],
                }],
                "rewrite_time": bd["rewrite"],
                "collector": [{
                    "name": "SimpleTopDocsCollector",
                    "reason": "search_top_hits",
                    "time_in_nanos": bd["reduce"],
                }],
            }],
            "engine": engine,
        }
        if self.segments:
            section["segments"] = list(self.segments)
        return section


def describe_plan(plan, bind) -> str:
    """Compact human-readable plan description for the profile response
    (``Query.toString()`` analog) — structural, never echoing document
    data beyond the query's own terms."""
    try:
        return plan.describe(bind)
    except Exception:
        return type(plan).__name__
