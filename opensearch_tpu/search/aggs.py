"""Aggregations: request parsing, per-segment collection, mergeable
partials, cross-shard reduce, response formatting.

Analog of the reference's two-phase model (per-shard collect via
``BucketCollector`` -> coordinator ``InternalAggregations.reduce``; ref
search/aggregations/BucketCollector.java:46,
bucket/histogram/DateHistogramAggregator.java,
bucket/terms/GlobalOrdinalsStringTermsAggregator.java,
action/search/QueryPhaseResultConsumer.java:178).  Collection is
array-oriented: bucket counts and metric partials are scatter-adds over
doc-value columns (ops/aggs.py).

The two phases are REAL phases here, crossing process boundaries:

- ``AggregationExecutor.collect`` runs shard-side and produces a
  JSON-serializable partial per agg (wire-safe: plain scalars/lists);
- ``reduce_aggs`` runs coordinator-side over any number of partials and
  produces the final response JSON.  The single-shard ``run`` is
  literally ``reduce_aggs(one partial)``, so every local test also
  validates the distributed path.

Approximate-on-purpose partials (matching the reference's contracts):
cardinality degrades from an exact value set to HyperLogLog registers
past ``precision_threshold`` (HyperLogLogPlusPlus.java analog);
percentiles degrade from raw values to weight-merged centroids past a
size cap (TDigest analog); terms are truncated to ``shard_size`` per
shard with ``doc_count_error_upper_bound`` computed from the smallest
included count of the shards that omitted a key.

Composition model: every bucket agg that selects a doc subset (filter,
filters, range, missing, global) recurses with a narrowed matched mask, so
arbitrary nesting works; terms/histogram support metric sub-aggs computed
in the same pass via two-level scatters.
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import re
from dataclasses import dataclass, field as dc_field

import numpy as np

import opensearch_tpu.common.jaxenv  # noqa: F401
import jax.numpy as jnp

from opensearch_tpu.common.errors import IllegalArgumentError, ParsingError
from opensearch_tpu.index.segment import pad_pow2
from opensearch_tpu.mapping.types import format_date_millis, parse_date_millis
from opensearch_tpu.ops import aggs as agg_ops

MAX_BUCKETS = 65536          # search.max_buckets default
CARD_EXACT_MAX = 3000        # cardinality precision_threshold default
PCT_RAW_MAX = 10_000         # percentiles: raw values above this compress
PCT_CENTROIDS = 1024
HLL_P = 12                   # 4096 registers, ~1.6% relative error
_METRIC_TYPES = {"min", "max", "sum", "avg", "value_count", "stats",
                 "cardinality", "percentiles", "extended_stats",
                 "weighted_avg", "percentile_ranks",
                 "median_absolute_deviation", "top_hits"}
_BUCKET_TYPES = {"terms", "histogram", "date_histogram", "range",
                 "date_range", "ip_range", "filter", "filters", "global",
                 "missing", "significant_terms", "rare_terms",
                 "multi_terms", "composite"}
# pipeline aggs (search/pipeline_aggs.py) parse like any agg but collect
# nothing shard-side; they run as a reduce post-pass
from opensearch_tpu.search.pipeline_aggs import (  # noqa: E402
    PIPELINE_TYPES as _PIPELINE_TYPES, apply_pipelines as _apply_pipelines)


_TUPLE_METRICS = {"min", "max", "sum", "avg", "value_count", "stats"}


def _metric_subs(req):
    """Sub-aggs that collect via the (sum, count, min, max) tuple
    machinery under terms/histogram/multi_terms/composite buckets.
    Pipeline subs collect nothing; top_hits has its own per-bucket path;
    anything else under these parents is an explicit 400 (the richer
    composition surface lives under filter/filters/range/global/missing,
    which recurse with full generality)."""
    out = []
    for s in req.subs:
        if s.type in _PIPELINE_TYPES or s.type == "top_hits":
            continue
        if s.type == "composite":
            raise IllegalArgumentError(
                "[composite] aggregation cannot be used with a parent "
                f"aggregation of type: [{req.type}]")
        if s.type not in _TUPLE_METRICS:
            raise IllegalArgumentError(
                f"[{req.type}] does not support [{s.type}] "
                "sub-aggregations (nest it under a filter instead)")
        out.append(s)
    return out


def _top_hits_subs(req):
    return [s for s in req.subs if s.type == "top_hits"]


@dataclass
class AggRequest:
    name: str
    type: str
    params: dict
    subs: list = dc_field(default_factory=list)


def parse_aggs(aggs_json: dict) -> list[AggRequest]:
    out = []
    for name, body in (aggs_json or {}).items():
        subs_json = body.get("aggs") or body.get("aggregations") or {}
        types = [k for k in body if k not in ("aggs", "aggregations", "meta")]
        if len(types) != 1:
            raise ParsingError(
                f"aggregation [{name}] must have exactly one type, got {types}")
        typ = types[0]
        if typ not in _METRIC_TYPES | _BUCKET_TYPES | _PIPELINE_TYPES:
            raise ParsingError(f"unknown aggregation type [{typ}]")
        subs = parse_aggs(subs_json)
        if typ in _METRIC_TYPES and subs:
            raise ParsingError(
                f"metric aggregation [{name}] cannot have sub-aggregations")
        if typ in _PIPELINE_TYPES and subs:
            raise ParsingError(
                f"pipeline aggregation [{name}] cannot have sub-aggregations")
        out.append(AggRequest(name, typ, body[typ], subs))
    return out


_DURATION = re.compile(r"^(\d+)(nanos|micros|ms|s|m|h|d)$")
_DUR_MS = {"nanos": 1e-6, "micros": 1e-3, "ms": 1, "s": 1000,
           "m": 60_000, "h": 3_600_000, "d": 86_400_000}
_CAL_FIXED_MS = {"second": 1000, "1s": 1000, "minute": 60_000, "1m": 60_000,
                 "hour": 3_600_000, "1h": 3_600_000, "day": 86_400_000,
                 "1d": 86_400_000, "week": 7 * 86_400_000, "1w": 7 * 86_400_000}


def _parse_duration_ms(s: str) -> int:
    m = _DURATION.match(str(s))
    if not m:
        raise IllegalArgumentError(f"failed to parse interval [{s}]")
    return int(m.group(1)) * _DUR_MS[m.group(2)]


def _floor_month(dt: _dt.datetime, months: int) -> _dt.datetime:
    total = dt.year * 12 + (dt.month - 1)
    total = (total // months) * months
    return _dt.datetime(total // 12, total % 12 + 1, 1, tzinfo=_dt.timezone.utc)


def _add_months(dt: _dt.datetime, months: int) -> _dt.datetime:
    total = dt.year * 12 + (dt.month - 1) + months
    return _dt.datetime(total // 12, total % 12 + 1, 1, tzinfo=_dt.timezone.utc)


def build_date_edges(lo: int, hi: int, calendar=None, fixed=None,
                     offset: int = 0) -> np.ndarray:
    """Ascending bucket edges (epoch millis) covering [lo, hi], aligned to
    the interval (Rounding.java analog, UTC only)."""
    if calendar in ("month", "1M", "quarter", "1q", "year", "1y"):
        months = {"month": 1, "1M": 1, "quarter": 3, "1q": 3,
                  "year": 12, "1y": 12}[calendar]
        start = _floor_month(
            _dt.datetime.fromtimestamp(lo / 1000, tz=_dt.timezone.utc), months)
        edges = [start]
        while edges[-1].timestamp() * 1000 <= hi:
            edges.append(_add_months(edges[-1], months))
        arr = np.asarray([int(e.timestamp() * 1000) for e in edges],
                         dtype=np.int64)
    else:
        if calendar is not None:
            ms = _CAL_FIXED_MS.get(calendar)
            if ms is None:
                raise IllegalArgumentError(
                    f"unknown calendar_interval [{calendar}]")
        else:
            ms = _parse_duration_ms(fixed)
        if calendar in ("week", "1w"):
            offset = (offset + 4 * 86_400_000) % ms   # epoch was a Thursday
        first = (lo - offset) // ms * ms + offset
        if first > lo:
            first -= ms
        n = (hi - first) // ms + 2
        if n > MAX_BUCKETS:
            raise IllegalArgumentError(
                f"trying to create too many buckets ({n} > {MAX_BUCKETS})")
        arr = first + ms * np.arange(n, dtype=np.int64)
    if len(arr) - 1 > MAX_BUCKETS:
        raise IllegalArgumentError(
            f"trying to create too many buckets ({len(arr) - 1} > {MAX_BUCKETS})")
    return arr


_NAMED_DATE_FORMATS = {
    "iso8601": "__iso8601__",
    "strict_date": "yyyy-MM-dd", "date": "yyyy-MM-dd",
    "strict_date_time": "yyyy-MM-dd'T'HH:mm:ss.SSSZ",
    "basic_date": "yyyyMMdd",
    "year_month_day": "yyyy-MM-dd",
    "strict_date_hour_minute_second": "yyyy-MM-dd'T'HH:mm:ss",
}


def _fmt_date(millis: int, fmt: str | None) -> str:
    if not fmt:
        return format_date_millis(int(millis))
    fmt = _NAMED_DATE_FORMATS.get(fmt, fmt)
    if fmt == "__iso8601__":
        return format_date_millis(int(millis))
    py = (fmt.replace("yyyy", "%Y").replace("MM", "%m").replace("dd", "%d")
          .replace("HH", "%H").replace("mm", "%M").replace("ss", "%S")
          .replace("'T'", "T"))
    dt = _dt.datetime.fromtimestamp(millis / 1000, tz=_dt.timezone.utc)
    return dt.strftime(py)


# ---------------------------------------------------------------------------
# Partial-tuple helpers (sum, count, min, max) — JSON-safe (no infinities).
# ---------------------------------------------------------------------------


def _ser_tuple(t) -> list:
    s, c, mn, mx = t
    return [float(s), int(c),
            None if not np.isfinite(mn) else float(mn),
            None if not np.isfinite(mx) else float(mx)]


def _merge_tuples(parts: list) -> tuple:
    s, c, mn, mx = 0.0, 0, np.inf, -np.inf
    for p in parts:
        if p is None:
            continue
        s += p[0]
        c += int(p[1])
        if p[2] is not None:
            mn = min(mn, p[2])
        if p[3] is not None:
            mx = max(mx, p[3])
    return s, c, mn, mx


def _top_hits_sort(sort):
    """(field, desc) for a top_hits sort spec; (None, True) = by _score.
    Numeric-field sorts only (the agg's common shape); anything else is
    a 400, not a silent misorder."""
    if sort is None:
        return None, True
    if isinstance(sort, list):
        if len(sort) != 1:
            raise IllegalArgumentError(
                "[top_hits] supports a single sort key")
        sort = sort[0]
    if isinstance(sort, str):
        return (None, True) if sort == "_score" else (sort, False)
    ((field, spec),) = sort.items()
    desc = (spec.get("order", "asc") if isinstance(spec, dict)
            else spec) == "desc"
    if field == "_score":
        return None, True
    return field, desc


def _finish_metric(typ: str, merged: tuple, params: dict | None = None):
    s, c, mn, mx = merged
    if typ == "sum":
        return {"value": s}
    if typ == "min":
        return {"value": mn if c else None}
    if typ == "max":
        return {"value": mx if c else None}
    if typ == "avg":
        return {"value": (s / c) if c else None}
    if typ == "value_count":
        return {"value": c}
    if typ == "stats":
        return {"count": c, "min": mn if c else None, "max": mx if c else None,
                "avg": (s / c) if c else None, "sum": s}
    raise IllegalArgumentError(f"metric type [{typ}] has no tuple finisher")


# ---------------------------------------------------------------------------
# HyperLogLog (cardinality past the exact threshold).
# ---------------------------------------------------------------------------


_SM_A = np.uint64(0x9E3779B97F4A7C15)
_SM_B = np.uint64(0xBF58476D1CE4E5B9)
_SM_C = np.uint64(0x94D049BB133111EB)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer (uint64 -> uint64) — stable across
    processes, so values hashed on different shard nodes land in the same
    HLL register."""
    with np.errstate(over="ignore"):
        x = x + _SM_A
        x = (x ^ (x >> np.uint64(30))) * _SM_B
        x = (x ^ (x >> np.uint64(27))) * _SM_C
        return x ^ (x >> np.uint64(31))


def _hash64_values(values) -> np.ndarray:
    """uint64 hashes of a homogeneous value batch: integer and float
    ndarrays vectorize straight off their dtype (the high-cardinality
    numeric path — no Python object churn); anything else falls back to
    per-value inspection, with blake2b for strings (ordinal vocabularies
    are bounded)."""
    if isinstance(values, np.ndarray):
        if np.issubdtype(values.dtype, np.integer):
            return _splitmix64(values.astype(np.int64).view(np.uint64))
        if np.issubdtype(values.dtype, np.floating):
            f = values.astype(np.float64)
            f = np.where(f == 0.0, 0.0, f)   # canonicalize -0.0
            return _splitmix64(f.view(np.uint64))
        values = values.tolist()
    vals = list(values)
    if not vals:
        return np.zeros(0, np.uint64)
    if all(isinstance(v, bool) or isinstance(v, (int, np.integer))
           for v in vals):
        return _splitmix64(np.asarray(vals, np.int64).view(np.uint64))
    if all(isinstance(v, (int, float, np.floating, np.integer))
           for v in vals):
        f = np.asarray(vals, np.float64)
        f = np.where(f == 0.0, 0.0, f)       # canonicalize -0.0
        return _splitmix64(f.view(np.uint64))
    return np.asarray([int.from_bytes(
        hashlib.blake2b(repr(v).encode(), digest_size=8).digest(),
        "little") for v in vals], np.uint64)


def _hll_add_hashes(regs: np.ndarray, hashes: np.ndarray) -> np.ndarray:
    idx = (hashes & np.uint64((1 << HLL_P) - 1)).astype(np.int64)
    w = hashes >> np.uint64(HLL_P)
    nbits = 64 - HLL_P
    # bit_length via successive shifts (log2 on uint64 is lossy)
    bit_length = np.zeros(len(hashes), np.int64)
    ww = w.copy()
    for shift in (32, 16, 8, 4, 2, 1):
        big = ww >= (np.uint64(1) << np.uint64(shift))
        bit_length = np.where(big, bit_length + shift, bit_length)
        ww = np.where(big, ww >> np.uint64(shift), ww)
    bit_length = np.where(w != 0, bit_length + 1, 0)
    # rank = leading zeros of the (64-P)-bit suffix + 1
    rank = (nbits - bit_length + 1).astype(np.uint8)
    np.maximum.at(regs, idx, rank)
    return regs


def _hll_from_values(values) -> np.ndarray:
    regs = np.zeros(1 << HLL_P, np.uint8)
    return _hll_add_hashes(regs, _hash64_values(values))


def _hll_estimate(regs: np.ndarray) -> int:
    m = regs.size
    alpha = 0.7213 / (1 + 1.079 / m)
    est = alpha * m * m / float(np.sum(2.0 ** -regs.astype(np.float64)))
    if est <= 2.5 * m:
        zeros = int((regs == 0).sum())
        if zeros:
            est = m * np.log(m / zeros)
    return int(round(est))


# ---------------------------------------------------------------------------
# Weighted centroids (percentiles past the raw cap) — TDigest-lite.
# ---------------------------------------------------------------------------


def _compress_centroids(values: np.ndarray, weights: np.ndarray,
                        n: int = PCT_CENTROIDS):
    order = np.argsort(values, kind="stable")
    v, w = values[order], weights[order]
    cw = np.cumsum(w)
    total = cw[-1]
    bins = np.minimum(((cw - w / 2.0) / total * n).astype(np.int64), n - 1)
    sums = np.bincount(bins, weights=v * w, minlength=n)
    ws = np.bincount(bins, weights=w, minlength=n)
    keep = ws > 0
    return sums[keep] / ws[keep], ws[keep]


def _weighted_percentile(v: np.ndarray, w: np.ndarray, p: float) -> float:
    """Linear-interpolated quantile over point masses; reproduces
    np.percentile exactly when every weight is 1."""
    order = np.argsort(v, kind="stable")
    v, w = v[order], w[order]
    pos = np.cumsum(w) - 1.0
    target = p / 100.0 * (w.sum() - 1.0)
    return float(np.interp(target, pos, v))


# ---------------------------------------------------------------------------
# Shard-side collection
# ---------------------------------------------------------------------------


class AggregationExecutor:
    """Runs an agg tree over per-segment matched masks.

    ``seg_views`` is [(seg, dseg, matched_jnp)] — the query phase's
    matched masks, one per segment.
    """

    def __init__(self, ctx, scores_of: dict | None = None):
        self.ctx = ctx               # compiler.ShardContext
        # per-segment query-phase scores (seg.name -> [n_pad] array);
        # only top_hits needs them, and only when sorting by _score
        self.scores_of = scores_of or {}

    def run(self, aggs_json: dict, seg_views: list) -> dict:
        """Single-shard convenience: collect + reduce of one partial."""
        return reduce_aggs(aggs_json, [self.collect(aggs_json, seg_views)])

    def collect(self, aggs_json: dict, seg_views: list) -> dict:
        """Shard-side phase: one JSON-serializable partial per agg."""
        reqs = parse_aggs(aggs_json)
        return {r.name: self._part_one(r, seg_views) for r in reqs}

    # -- helpers ----------------------------------------------------------

    def _field_type(self, req, caller):
        field = req.params.get("field")
        if field is None:
            if caller == "terms":
                raise ParsingError(
                    "Required one of fields [field, script], but none "
                    "were specified. ")
            raise ParsingError(f"[{caller}] aggregation requires a [field]")
        ft = self.ctx.field_type(field)
        if ft is not None and ft.dv_kind == "none":
            raise IllegalArgumentError(
                f"Text fields are not optimised for operations that require "
                f"per-document field data like aggregations and sorting, so "
                f"these operations are disabled by default. Please use a "
                f"keyword field instead. Alternatively, set fielddata=true "
                f"on [{field}]")
        return field, ft

    def _numeric_column(self, seg, field):
        return seg.numeric_dv.get(field)

    def _dev_numeric(self, dseg, field):
        return dseg.numeric.get(field)

    # -- dispatch ---------------------------------------------------------

    def _part_one(self, req, seg_views) -> dict:
        if req.type in _PIPELINE_TYPES:
            return {"t": "pipeline"}     # reduce-side only, no shard work
        if req.type in ("min", "max", "sum", "avg", "value_count", "stats"):
            return self._part_metric(req, seg_views)
        fn = getattr(self, f"_part_{req.type}", None)
        if fn is None:
            raise ParsingError(f"unknown aggregation type [{req.type}]")
        return fn(req, seg_views)

    # -- metrics ----------------------------------------------------------

    def _collect_metric_partials(self, field, seg_views):
        s = 0.0
        c = 0
        mn, mx = np.inf, -np.inf
        for seg, dseg, matched in seg_views:
            col = self._dev_numeric(dseg, field)
            if col is None:
                continue
            ss, cc, mnn, mxx = agg_ops.masked_metrics(
                col["values"], col["value_docs"], matched)
            s += float(ss)
            c += int(cc)
            mn = min(mn, float(mnn))
            mx = max(mx, float(mxx))
        return s, c, mn, mx

    def _part_metric(self, req, seg_views) -> dict:
        field, ft = self._field_type(req, req.type)
        if (req.type == "value_count" and ft is not None
                and ft.dv_kind == "ordinal"):
            total = 0
            for seg, dseg, matched in seg_views:
                col = dseg.ordinal.get(field)
                if col is None:
                    continue
                ok = matched[col["value_docs"]] & (col["ords"] >= 0)
                total += int(ok.sum())
            return {"t": "metric", "v": [0.0, total, None, None]}
        return {"t": "metric",
                "v": _ser_tuple(self._collect_metric_partials(field,
                                                              seg_views))}

    def _part_cardinality(self, req, seg_views) -> dict:
        """Exact set below precision_threshold; STREAMING degradation to
        HLL registers past it — the set never grows beyond the threshold
        no matter how many distinct values the segments hold (r3 Weak #5:
        bounded memory)."""
        field, ft = self._field_type(req, "cardinality")
        threshold = int(req.params.get("precision_threshold",
                                       CARD_EXACT_MAX))
        distinct: set = set()
        regs = None
        for seg, dseg, matched in seg_views:
            m = np.asarray(matched)
            if ft is not None and ft.dv_kind == "ordinal":
                dv = seg.ordinal_dv.get(field)
                if dv is None:
                    continue
                ok = m[dv.value_docs] if len(dv.value_docs) else \
                    np.zeros(0, bool)
                new = [dv.ord_terms[o] for o in np.unique(dv.ords[ok])]
            else:
                dv = seg.numeric_dv.get(field)
                if dv is None:
                    continue
                ok = m[dv.value_docs] if len(dv.value_docs) else \
                    np.zeros(0, bool)
                new = np.unique(dv.values[ok])   # stays an ndarray:
                # the HLL path hashes it straight off the dtype
            if regs is None:
                # exact while possible: the union may dedup below the
                # threshold even when count-sums exceed it
                distinct.update(new if isinstance(new, list)
                                else new.tolist())
                if len(distinct) > threshold:
                    regs = _hll_from_values(distinct)
                    distinct.clear()
            else:
                regs = _hll_add_hashes(regs, _hash64_values(new))
        if regs is None:
            return {"t": "card", "kind": "set",
                    "v": sorted(distinct, key=repr), "thr": threshold}
        return {"t": "card", "kind": "hll", "regs": regs.tolist(),
                "thr": threshold}

    def _part_percentiles(self, req, seg_views) -> dict:
        """Small matched sets stay raw (exact quantiles); past the cap the
        DEVICE sorts and bins values into equal-weight centroids
        (ops/aggs.py masked_centroids) — host memory stays O(PCT_CENTROIDS)
        per segment no matter how many values matched (SURVEY §7.2's
        on-device agg mandate; fixes r3 Weak #5's unbounded
        materialization)."""
        field, _ = self._field_type(req, "percentiles")
        raw_chunks = []
        cent_m, cent_w = [], []
        for seg, dseg, matched in seg_views:
            dv = seg.numeric_dv.get(field)
            col = self._dev_numeric(dseg, field)
            if dv is None or col is None or not len(dv.value_docs):
                continue
            n_matched = int(np.asarray(matched[col["value_docs"]]).sum())
            if n_matched == 0:
                continue
            if n_matched <= PCT_RAW_MAX:
                ok = np.asarray(matched)[dv.value_docs]
                raw_chunks.append(dv.values[ok].astype(np.float64))
            else:
                means, weights = agg_ops.masked_centroids(
                    col["values"], col["value_docs"], matched,
                    n_cent=PCT_CENTROIDS)
                means, weights = np.asarray(means), np.asarray(weights)
                keep = weights > 0
                cent_m.append(means[keep])
                cent_w.append(weights[keep].astype(np.float64))
        if not raw_chunks and not cent_m:
            return {"t": "pct", "kind": "raw", "v": []}
        if cent_m or sum(len(c) for c in raw_chunks) > PCT_RAW_MAX:
            if raw_chunks:
                allv = np.concatenate(raw_chunks)
                cent_m.append(allv)
                cent_w.append(np.ones_like(allv))
            m = np.concatenate(cent_m)
            w = np.concatenate(cent_w)
            if len(m) > 4 * PCT_CENTROIDS:
                m, w = _compress_centroids(m, w)
            return {"t": "pct", "kind": "cent",
                    "m": m.tolist(), "w": w.tolist()}
        allv = np.concatenate(raw_chunks)
        return {"t": "pct", "kind": "raw", "v": allv.tolist()}

    def _part_percentile_ranks(self, req, seg_views) -> dict:
        """Same mergeable value sketch as percentiles (raw below the cap,
        equal-weight centroids above); the rank direction happens at
        reduce.  Ref metrics/PercentileRanksAggregationBuilder.java."""
        if req.params.get("values") is None:
            raise ParsingError(
                "[percentile_ranks] requires a [values] array")
        return self._part_percentiles(req, seg_views)

    def _part_median_absolute_deviation(self, req, seg_views) -> dict:
        """MAD over the same sketch (exact on raw partials; on centroid
        partials the weighted-median deviation is the TDigest-style
        approximation the reference documents).  Ref
        metrics/MedianAbsoluteDeviationAggregator.java."""
        return self._part_percentiles(req, seg_views)

    def _part_extended_stats(self, req, seg_views) -> dict:
        """stats + sum_of_squares partial (the extra moment the variance
        family needs).  Ref metrics/ExtendedStatsAggregator.java."""
        field, _ = self._field_type(req, "extended_stats")
        s = sq = 0.0
        c = 0
        mn, mx = np.inf, -np.inf
        for seg, dseg, matched in seg_views:
            dv = seg.numeric_dv.get(field)
            if dv is None or not len(dv.value_docs):
                continue
            ok = np.asarray(matched)[dv.value_docs]
            v = dv.values[ok].astype(np.float64)
            if not len(v):
                continue
            s += float(v.sum())
            sq += float((v * v).sum())
            c += int(len(v))
            mn = min(mn, float(v.min()))
            mx = max(mx, float(v.max()))
        return {"t": "estats",
                "v": _ser_tuple((s, c, mn, mx)) + [float(sq)]}

    def _part_weighted_avg(self, req, seg_views) -> dict:
        """sum(value*weight) / sum(weight) partial.  Multi-valued value
        fields weight every value by the doc's (single-valued) weight;
        docs missing the weight field are skipped, docs missing the
        value field use [value.missing] if set.  Ref
        metrics/WeightedAvgAggregator.java."""
        vcfg = req.params.get("value") or {}
        wcfg = req.params.get("weight") or {}
        vfield, wfield = vcfg.get("field"), wcfg.get("field")
        if not vfield or not wfield:
            raise ParsingError(
                "[weighted_avg] requires [value.field] and [weight.field]")
        v_missing = vcfg.get("missing")
        vw_sum = w_sum = 0.0
        for seg, dseg, matched in seg_views:
            wdv = seg.numeric_dv.get(wfield)
            if wdv is None or not len(wdv.value_docs):
                continue
            m = np.asarray(matched)
            weight_of = np.zeros(seg.n_docs)
            has_w = np.zeros(seg.n_docs, bool)
            wok = m[wdv.value_docs]
            weight_of[wdv.value_docs[wok]] = wdv.values[wok].astype(np.float64)
            has_w[wdv.value_docs[wok]] = True
            vdv = seg.numeric_dv.get(vfield)
            got_v = np.zeros(seg.n_docs, bool)
            if vdv is not None and len(vdv.value_docs):
                vok = m[vdv.value_docs] & has_w[vdv.value_docs]
                vd = vdv.value_docs[vok]
                vw_sum += float((vdv.values[vok].astype(np.float64)
                                 * weight_of[vd]).sum())
                # each doc's weight counts once no matter how many values
                got_v[vd] = True
                w_sum += float(weight_of[np.nonzero(got_v)[0]].sum())
            if v_missing is not None:
                fill = has_w & ~got_v & m[: seg.n_docs]
                vw_sum += float(v_missing) * float(weight_of[fill].sum())
                w_sum += float(weight_of[fill].sum())
        return {"t": "wavg", "v": [vw_sum, w_sum]}

    def _part_top_hits(self, req, seg_views) -> dict:
        """Per-shard top hits by query score (or a numeric field sort),
        serialized with their _source so the coordinator merge needs no
        second fetch round-trip.  Ref metrics/TopHitsAggregator.java."""
        hits, total = self._top_hits_collect(req, seg_views)
        return {"t": "tophits", "hits": hits, "total": total}

    def _top_hits_collect(self, req, seg_views):
        from opensearch_tpu.search.fetch import filter_source

        size = int(req.params.get("size", 3))
        from_ = int(req.params.get("from", 0))
        want = from_ + size
        sort_field, sort_desc = _top_hits_sort(req.params.get("sort"))
        source_spec = req.params.get("_source")
        rows = []
        total = 0
        for seg, dseg, matched in seg_views:
            m = np.asarray(matched)[: seg.n_docs]
            docs = np.nonzero(m)[0]
            total += int(len(docs))
            if not len(docs):
                continue
            if sort_field is None:
                scores = self.scores_of.get(seg.seg_id)
                key = (np.asarray(scores)[: seg.n_docs][docs]
                       if scores is not None
                       else np.zeros(len(docs)))
                desc = True
            else:
                dv = seg.numeric_dv.get(sort_field)
                key = np.full(len(docs), np.nan)
                if dv is not None and len(dv.value_docs):
                    col = np.full(seg.n_docs, np.nan)
                    col[dv.value_docs[::-1]] = dv.values[::-1]  # first value
                    key = col[docs]
                desc = sort_desc
            nan_safe = np.where(np.isnan(key), -np.inf if desc else np.inf,
                                key)                   # missing sorts last
            order = np.argsort(-nan_safe if desc else nan_safe,
                               kind="stable")[:want]
            for i in order:
                d = int(docs[i])
                k = key[i]
                rows.append((float(k) if np.isfinite(k) else None, seg, d))
        last = -np.inf if (sort_field is None or sort_desc) else np.inf
        rows.sort(key=lambda r: r[0] if r[0] is not None else last,
                  reverse=(sort_field is None or sort_desc))
        out = []
        for k, seg, d in rows[:want]:
            hit = {"_id": seg.doc_ids[d],
                   "_score": k if sort_field is None else None}
            src = filter_source(seg.source(d), source_spec)
            if src is not None:
                hit["_source"] = src
            if sort_field is not None:
                hit["sort"] = [k]
            out.append(hit)
        return out, total

    # -- terms ------------------------------------------------------------

    def _part_terms(self, req, seg_views) -> dict:
        field, ft = self._field_type(req, "terms")
        size = int(req.params.get("size", 10))
        order = req.params.get("order", {"_count": "desc"})
        missing = req.params.get("missing")
        if ft is None:
            if missing is None:
                return {"t": "terms", "tn": None, "dk": None,
                        "buckets": [], "others": 0, "min_inc": 0}
            # unmapped field + missing: every matched doc buckets under
            # the missing value (TermsAggregatorFactory unmapped+missing)
            total = sum(int(np.asarray(m)[: s.n_docs].sum())
                        for s, _d, m in seg_views)
            value_type = req.params.get("value_type")
            if value_type == "date":
                tn, dk = "date", "long"
                missing = int(parse_date_millis(missing))
            elif isinstance(missing, bool):
                tn, dk, missing = "boolean", "long", int(missing)
            elif isinstance(missing, str):
                tn, dk = "keyword", "ordinal"
            elif isinstance(missing, int):
                tn, dk = "long", "long"
            else:
                tn, dk = "double", "double"
            buckets = [[missing, total, {}]] if total else []
            return {"t": "terms", "tn": tn, "dk": dk, "buckets": buckets,
                    "others": 0, "min_inc": 0}
        msubs = _metric_subs(req)
        if ft.dv_kind == "ordinal":
            merged, sub_parts = self._terms_ordinal(field, seg_views, msubs)
        else:
            merged, sub_parts = self._terms_numeric(field, seg_views, msubs)
        if int(req.params.get("min_doc_count", 1)) == 0:
            # zero-count buckets: every term of the index joins with 0
            # (TermsAggregator's buildEmptyAggregation grid fill)
            for seg, _d, _m in seg_views:
                if ft.dv_kind == "ordinal":
                    dv = seg.ordinal_dv.get(field)
                    for t in (dv.ord_terms if dv is not None else ()):
                        merged.setdefault(t, 0)
                else:
                    dv = seg.numeric_dv.get(field)
                    if dv is not None:
                        for v in np.unique(dv.values):
                            key = (float(v) if dv.kind == "double"
                                   else int(v))
                            merged.setdefault(key, 0)
        if missing is not None:
            # docs without a value for the field take the missing value
            absent = 0
            for seg, dseg, matched in seg_views:
                m = np.asarray(matched)[: seg.n_docs]
                dv = (seg.ordinal_dv if ft.dv_kind == "ordinal"
                      else seg.numeric_dv).get(field)
                with_val = (len(np.unique(dv.value_docs[
                    m[dv.value_docs]])) if dv is not None
                    and len(dv.value_docs) else 0)
                absent += int(m.sum()) - with_val
            if absent:
                key = (missing if ft.dv_kind == "ordinal"
                       else (float(missing) if ft.dv_kind == "double"
                             else int(parse_date_millis(missing)
                                      if ft.type_name == "date"
                                      and isinstance(missing, str)
                                      else missing)))
                merged[key] = merged.get(key, 0) + absent
        shard_size = int(req.params.get("shard_size")
                         or max(size, int(size * 1.5 + 10)))
        items = sorted(merged.items(), key=_terms_order_key(order))
        kept, tail = items[:shard_size], items[shard_size:]
        others = sum(c for _k, c in tail)
        # the error-bound contract only holds for count-descending order
        is_count_desc = _is_count_desc(order)
        min_inc = kept[-1][1] if (tail and kept and is_count_desc) else 0
        buckets = []
        th_subs = _top_hits_subs(req)
        for key, count in kept:
            subs = {sub.name: _ser_tuple(sub_parts.get(
                (sub.name, key), (0.0, 0, np.inf, -np.inf)))
                for sub in msubs}
            for sub in th_subs:     # per-bucket top hits: narrowed mask
                subs[sub.name] = self._part_top_hits(
                    sub, self._terms_key_views(field, ft, seg_views, key))
            buckets.append([key, int(count), subs])
        return {"t": "terms", "tn": ft.type_name, "dk": ft.dv_kind,
                "buckets": buckets, "others": int(others),
                "min_inc": int(min_inc)}

    def _terms_key_views(self, field, ft, seg_views, key):
        """seg_views narrowed to docs holding ``key`` in ``field``."""
        out = []
        for seg, dseg, matched in seg_views:
            m = np.asarray(matched)[: seg.n_docs]
            mask = np.zeros(seg.n_docs, bool)
            if ft.dv_kind == "ordinal":
                dv = seg.ordinal_dv.get(field)
                if dv is not None and len(dv.value_docs):
                    o = dv.term_to_ord.get(key, -1)
                    if o >= 0:
                        mask[dv.value_docs[dv.ords == o]] = True
            else:
                dv = seg.numeric_dv.get(field)
                if dv is not None and len(dv.value_docs):
                    mask[dv.value_docs[dv.values == key]] = True
            out.append((seg, dseg, m & mask))
        return out

    def _terms_ordinal(self, field, seg_views, subs):
        merged: dict = {}
        sub_parts: dict = {}
        for seg, dseg, matched in seg_views:
            dv = seg.ordinal_dv.get(field)
            col = dseg.ordinal.get(field)
            if dv is None or col is None:
                continue
            n_pad_b = pad_pow2(len(dv.ord_terms) + 1)
            counts = np.asarray(agg_ops.ordinal_counts(
                col["ords"], col["value_docs"], matched,
                n_buckets_pad=n_pad_b))
            nz = np.nonzero(counts[: len(dv.ord_terms)])[0]
            for o in nz:
                term = dv.ord_terms[o]
                merged[term] = merged.get(term, 0) + int(counts[o])
            for sub in subs:
                sf, sft = self._field_type(sub, sub.type)
                scol = self._dev_numeric(dseg, sf)
                if scol is None:
                    continue
                entry_ok = matched[col["value_docs"]] & (col["ords"] >= 0)
                per_doc = agg_ops.per_doc_partials(
                    scol["values"], scol["value_docs"], matched,
                    n_pad=dseg.n_pad)
                s, c, mn, mx = agg_ops.scatter_partials_to_buckets(
                    col["value_docs"], col["ords"], entry_ok, per_doc,
                    n_buckets_pad=n_pad_b)
                s, c = np.asarray(s), np.asarray(c)
                mn, mx = np.asarray(mn), np.asarray(mx)
                for o in nz:
                    term = dv.ord_terms[o]
                    key = (sub.name, term)
                    ps, pc, pmn, pmx = sub_parts.get(key,
                                                     (0.0, 0, np.inf, -np.inf))
                    sub_parts[key] = (ps + float(s[o]), pc + int(c[o]),
                                      min(pmn, float(mn[o])),
                                      max(pmx, float(mx[o])))
        return merged, sub_parts

    def _terms_numeric(self, field, seg_views, subs):
        merged: dict = {}
        sub_parts: dict = {}
        for seg, dseg, matched in seg_views:
            dv = seg.numeric_dv.get(field)
            if dv is None or not len(dv.value_docs):
                continue
            m = np.asarray(matched)
            ok = m[dv.value_docs]
            vals, docs = dv.values[ok], dv.value_docs[ok]
            # docs count once per distinct value; keep the native dtype for
            # the dedup — a float64 cast would collapse longs above 2^53
            pair_dtype = np.int64 if dv.kind == "long" else np.float64
            pairs = np.unique(np.stack([vals.astype(pair_dtype),
                                        docs.astype(pair_dtype)]), axis=1)
            uniq_vals, counts = np.unique(pairs[0], return_counts=True)
            for v, c in zip(uniq_vals, counts):
                key = float(v) if dv.kind == "double" else int(v)
                merged[key] = merged.get(key, 0) + int(c)
            for sub in subs:
                sf, _sft = self._field_type(sub, sub.type)
                sdv = seg.numeric_dv.get(sf)
                if sdv is None:
                    continue
                per_doc_sum = np.zeros(seg.n_docs)
                per_doc_cnt = np.zeros(seg.n_docs, np.int64)
                per_doc_min = np.full(seg.n_docs, np.inf)
                per_doc_max = np.full(seg.n_docs, -np.inf)
                sok = m[sdv.value_docs] if len(sdv.value_docs) else np.zeros(0, bool)
                np.add.at(per_doc_sum, sdv.value_docs[sok],
                          sdv.values[sok].astype(np.float64))
                np.add.at(per_doc_cnt, sdv.value_docs[sok], 1)
                np.minimum.at(per_doc_min, sdv.value_docs[sok],
                              sdv.values[sok].astype(np.float64))
                np.maximum.at(per_doc_max, sdv.value_docs[sok],
                              sdv.values[sok].astype(np.float64))
                for v, d in zip(pairs[0], pairs[1].astype(np.int64)):
                    key0 = v if dv.kind == "double" else int(v)
                    key = (sub.name, key0)
                    ps, pc, pmn, pmx = sub_parts.get(key,
                                                     (0.0, 0, np.inf, -np.inf))
                    sub_parts[key] = (ps + per_doc_sum[d],
                                      pc + int(per_doc_cnt[d]),
                                      min(pmn, per_doc_min[d]),
                                      max(pmx, per_doc_max[d]))
        return merged, sub_parts

    # -- significant / rare / multi terms ---------------------------------

    def _field_term_counts(self, field, ft, seg, matched_np) -> dict:
        """term -> doc_count over one segment's matched mask (each doc
        counts once per distinct value)."""
        out: dict = {}
        if ft.dv_kind == "ordinal":
            dv = seg.ordinal_dv.get(field)
            if dv is None or not len(dv.value_docs):
                return out
            ok = matched_np[dv.value_docs]
            ords, counts = np.unique(dv.ords[ok], return_counts=True)
            for o, c in zip(ords, counts):
                if o >= 0:
                    out[dv.ord_terms[o]] = int(c)
        else:
            dv = seg.numeric_dv.get(field)
            if dv is None or not len(dv.value_docs):
                return out
            ok = matched_np[dv.value_docs]
            pair_dtype = np.int64 if dv.kind == "long" else np.float64
            pairs = np.unique(np.stack(
                [dv.values[ok].astype(pair_dtype),
                 dv.value_docs[ok].astype(pair_dtype)]), axis=1)
            vals, counts = np.unique(pairs[0], return_counts=True)
            for v, c in zip(vals, counts):
                key = float(v) if dv.kind == "double" else int(v)
                out[key] = int(c)
        return out

    def _part_significant_terms(self, req, seg_views) -> dict:
        """Foreground (matched) vs background (whole live segment) term
        counts; the JLH scoring happens at reduce over the merged totals.
        Ref bucket/terms/SignificantTermsAggregatorFactory.java +
        heuristic/JLHScore.java."""
        field, ft = self._field_type(req, "significant_terms")
        if ft is None:
            return {"t": "sig", "tn": None, "dk": None, "fg_total": 0,
                    "bg_total": 0, "buckets": []}
        fg: dict = {}
        bg: dict = {}
        fg_total = bg_total = 0
        for seg, dseg, matched in seg_views:
            m = np.asarray(matched)[: seg.n_docs]
            live = np.asarray(self.ctx.live_jnp(seg, dseg))[: seg.n_docs]
            fg_total += int(m.sum())
            bg_total += int(live.sum())
            for t, c in self._field_term_counts(field, ft, seg, m).items():
                fg[t] = fg.get(t, 0) + c
            for t, c in self._field_term_counts(field, ft, seg,
                                                live).items():
                bg[t] = bg.get(t, 0) + c
        shard_size = int(req.params.get("shard_size")
                         or max(int(req.params.get("size", 10)) * 2, 100))
        rows = [[t, c, bg.get(t, c)] for t, c in fg.items()]
        rows.sort(key=lambda r: -_jlh(r[1], fg_total, r[2], bg_total))
        return {"t": "sig", "tn": ft.type_name, "dk": ft.dv_kind,
                "fg_total": fg_total, "bg_total": bg_total,
                "buckets": rows[:shard_size]}

    def _part_rare_terms(self, req, seg_views) -> dict:
        """Counts for terms at-or-below max_doc_count, plus the names of
        terms already over it ('over'): a term rare on every shard can
        still sum over the threshold, and a term omitted by one shard is
        ambiguous without the over-list (the reference uses a CuckooFilter
        for the same exclusion — bucket/terms/RareTermsAggregator).."""
        field, ft = self._field_type(req, "rare_terms")
        max_dc = int(req.params.get("max_doc_count", 1))
        if max_dc < 1 or max_dc > 100:
            raise IllegalArgumentError(
                "[max_doc_count] must be in [1, 100]")
        if ft is None:
            return {"t": "rare", "tn": None, "dk": None, "buckets": [],
                    "over": []}
        counts: dict = {}
        for seg, dseg, matched in seg_views:
            m = np.asarray(matched)[: seg.n_docs]
            for t, c in self._field_term_counts(field, ft, seg, m).items():
                counts[t] = counts.get(t, 0) + c
        rare = [[t, c] for t, c in counts.items() if c <= max_dc]
        over = [t for t, c in counts.items() if c > max_dc]
        return {"t": "rare", "tn": ft.type_name, "dk": ft.dv_kind,
                "buckets": rare, "over": over}

    def _part_multi_terms(self, req, seg_views) -> dict:
        """Buckets per combination of values across N fields (cartesian
        per doc, the reference's MultiTermsAggregator).  Metric sub-aggs
        accumulate per combination in the same pass."""
        specs = req.params.get("terms")
        if not isinstance(specs, list) or len(specs) < 2:
            raise ParsingError(
                "[multi_terms] requires at least two [terms] sources")
        if _top_hits_subs(req):
            raise IllegalArgumentError(
                "[multi_terms] does not support [top_hits] "
                "sub-aggregations (nest top_hits under terms or a filter)")
        fields = []
        for spec in specs:
            f = spec.get("field")
            if not f:
                raise ParsingError("[multi_terms] source requires [field]")
            fields.append((f, self.ctx.field_type(f)))
        msubs = _metric_subs(req)
        merged: dict = {}
        sub_parts: dict = {}
        for seg, dseg, matched in seg_views:
            m = np.asarray(matched)[: seg.n_docs]
            per_field = [self._doc_values_lists(f, ft, seg, m)
                         for f, ft in fields]
            docs = set(per_field[0])
            for vals in per_field[1:]:
                docs &= set(vals)
            sub_cols = [self._doc_metric_tuples(sub, seg, m)
                        for sub in msubs]
            import itertools

            for d in docs:
                combos = list(itertools.product(
                    *[vals[d] for vals in per_field]))
                for key in combos:
                    merged[key] = merged.get(key, 0) + 1
                for si, sub in enumerate(msubs):
                    tup = sub_cols[si].get(d)
                    if tup is None:
                        continue
                    for key in combos:
                        prev = sub_parts.get((sub.name, key),
                                             (0.0, 0, np.inf, -np.inf))
                        sub_parts[(sub.name, key)] = (
                            prev[0] + tup[0], prev[1] + tup[1],
                            min(prev[2], tup[2]), max(prev[3], tup[3]))
        size = int(req.params.get("size", 10))
        shard_size = int(req.params.get("shard_size")
                         or max(size, int(size * 1.5 + 10)))
        order = req.params.get("order", {"_count": "desc"})
        items = sorted(merged.items(), key=_terms_order_key(order))
        kept, tail = items[:shard_size], items[shard_size:]
        min_inc = (kept[-1][1] if tail and kept and _is_count_desc(order)
                   else 0)
        buckets = []
        for key, count in kept:
            subs = {sub.name: _ser_tuple(sub_parts.get(
                (sub.name, key), (0.0, 0, np.inf, -np.inf)))
                for sub in msubs}
            buckets.append([list(key), int(count), subs])
        return {"t": "mterms", "buckets": buckets,
                "others": sum(c for _k, c in tail), "min_inc": int(min_inc)}

    def _doc_values_lists(self, field, ft, seg, matched_np) -> dict:
        """doc -> list of values for one field (matched docs only)."""
        out: dict = {}
        if ft is not None and ft.dv_kind == "ordinal":
            dv = seg.ordinal_dv.get(field)
            if dv is None:
                return out
            ok = matched_np[dv.value_docs] & (dv.ords >= 0)
            for d, o in zip(dv.value_docs[ok], dv.ords[ok]):
                out.setdefault(int(d), []).append(dv.ord_terms[o])
        else:
            dv = seg.numeric_dv.get(field)
            if dv is None:
                return out
            ok = matched_np[dv.value_docs]
            for d, v in zip(dv.value_docs[ok], dv.values[ok]):
                out.setdefault(int(d), []).append(
                    float(v) if dv.kind == "double" else int(v))
        return out

    def _doc_metric_tuples(self, sub, seg, matched_np) -> dict:
        """doc -> (sum, count, min, max) for one metric sub-agg field."""
        sf, _sft = self._field_type(sub, sub.type)
        dv = seg.numeric_dv.get(sf)
        out: dict = {}
        if dv is None:
            return out
        ok = matched_np[dv.value_docs]
        for d, v in zip(dv.value_docs[ok], dv.values[ok].astype(np.float64)):
            prev = out.get(int(d), (0.0, 0, np.inf, -np.inf))
            out[int(d)] = (prev[0] + v, prev[1] + 1, min(prev[2], v),
                           max(prev[3], v))
        return out

    # -- composite --------------------------------------------------------

    def _part_composite(self, req, seg_views) -> dict:
        """Paginated multi-source buckets: each shard emits its first
        ``size`` keys after ``after`` in composite order, so the merged
        union always contains the global first ``size`` (ref
        bucket/composite/CompositeAggregator.java).  Sources: terms,
        histogram, date_histogram."""
        sources = _composite_sources(req)
        if int(req.params.get("size", 10)) > MAX_BUCKETS:
            raise IllegalArgumentError(
                f"Trying to create too many buckets "
                f"({req.params.get('size')} > {MAX_BUCKETS})")
        if _top_hits_subs(req):
            raise IllegalArgumentError(
                "[composite] does not support [top_hits] "
                "sub-aggregations (nest top_hits under terms or a filter)")
        size = int(req.params.get("size", 10))
        after = req.params.get("after")
        if after is not None:
            missing_srcs = [s[0] for s in sources if s[0] not in after]
            if missing_srcs:
                raise ParsingError(
                    f"[composite] after key is missing sources "
                    f"{missing_srcs}")
        if after is not None:
            vals = []
            for name, _f, _x, _o, kind, _fmt in sources:
                v = after[name]
                if kind == "date" and isinstance(v, str) \
                        and not v.lstrip("-").isdigit():
                    v = parse_date_millis(v)
                vals.append(v)
            after_key = tuple(vals)
        else:
            after_key = None
        msubs = _metric_subs(req)
        merged: dict = {}
        sub_parts: dict = {}
        for seg, dseg, matched in seg_views:
            m = np.asarray(matched)[: seg.n_docs]
            per_source = []
            for name, field, xform, _order, _kind, _fmt in sources:
                ft = self.ctx.field_type(field)
                vals = self._doc_values_lists(field, ft, seg, m)
                if xform is not None:
                    vals = {d: sorted({xform(v) for v in vs})
                            for d, vs in vals.items()}
                per_source.append(vals)
            docs = set(per_source[0])
            for vals in per_source[1:]:
                docs &= set(vals)
            sub_cols = [self._doc_metric_tuples(sub, seg, m)
                        for sub in msubs]
            import itertools

            for d in docs:
                combos = set(itertools.product(
                    *[vals[d] for vals in per_source]))
                for key in combos:
                    merged[key] = merged.get(key, 0) + 1
                for si, sub in enumerate(msubs):
                    tup = sub_cols[si].get(d)
                    if tup is None:
                        continue
                    for key in combos:
                        prev = sub_parts.get((sub.name, key),
                                             (0.0, 0, np.inf, -np.inf))
                        sub_parts[(sub.name, key)] = (
                            prev[0] + tup[0], prev[1] + tup[1],
                            min(prev[2], tup[2]), max(prev[3], tup[3]))
        cmp_key = _composite_sort_key(sources)
        items = sorted(merged.items(), key=lambda kv: cmp_key(kv[0]))
        if after_key is not None:
            ak = cmp_key(after_key)
            items = [kv for kv in items if cmp_key(kv[0]) > ak]
        items = items[:size]
        buckets = []
        for key, count in items:
            subs = {sub.name: _ser_tuple(sub_parts.get(
                (sub.name, key), (0.0, 0, np.inf, -np.inf)))
                for sub in msubs}
            buckets.append([list(key), int(count), subs])
        return {"t": "composite", "buckets": buckets}

    # -- histograms -------------------------------------------------------

    def _part_histogram(self, req, seg_views) -> dict:
        field, ft = self._field_type(req, "histogram")
        interval = float(req.params["interval"])
        if interval <= 0:
            raise IllegalArgumentError("[interval] must be > 0")
        offset = float(req.params.get("offset", 0))
        s, c, mn, mx = self._collect_metric_partials(field, seg_views)
        if not c:
            return {"t": "hist", "mn": None, "mx": None, "buckets": []}
        first = np.floor((mn - offset) / interval) * interval + offset
        n = int((mx - first) // interval) + 2
        if n > MAX_BUCKETS:
            raise IllegalArgumentError(
                f"trying to create too many buckets ({n} > {MAX_BUCKETS})")
        edges = first + interval * np.arange(n, dtype=np.float64)
        buckets = self._histogram_buckets(req, field, seg_views, edges,
                                          keys=edges[:-1])
        return {"t": "hist", "mn": float(mn), "mx": float(mx),
                "buckets": buckets}

    def _part_date_histogram(self, req, seg_views) -> dict:
        field, ft = self._field_type(req, "date_histogram")
        calendar = req.params.get("calendar_interval")
        fixed = req.params.get("fixed_interval") or req.params.get("interval")
        if calendar is None and fixed is None:
            raise ParsingError(
                "date_histogram requires calendar_interval or fixed_interval")
        offset = _dh_offset(req)
        s, c, mn, mx = self._collect_metric_partials(field, seg_views)
        if not c:
            return {"t": "hist", "mn": None, "mx": None, "buckets": []}
        edges = build_date_edges(int(mn), int(mx), calendar=calendar,
                                 fixed=None if calendar else fixed,
                                 offset=int(offset))
        buckets = self._histogram_buckets(req, field, seg_views,
                                          edges.astype(np.float64),
                                          keys=edges[:-1])
        return {"t": "hist", "mn": int(mn), "mx": int(mx),
                "buckets": buckets}

    def _histogram_buckets(self, req, field, seg_views, edges, keys) -> list:
        """Shared histogram inner loop: per-bucket counts + metric
        sub-partials over aligned edges; emits only non-empty buckets
        (the reduce regenerates the full grid for gap filling)."""
        if _top_hits_subs(req):
            raise IllegalArgumentError(
                f"[{req.type}] does not support [top_hits] "
                "sub-aggregations (nest top_hits under terms or a filter)")
        n_buckets = len(keys)
        n_pad_b = pad_pow2(n_buckets + 1)
        totals = np.zeros(n_buckets, np.int64)
        msubs = _metric_subs(req)
        sub_parts = {sub.name: [np.zeros(n_buckets),
                                np.zeros(n_buckets, np.int64),
                                np.full(n_buckets, np.inf),
                                np.full(n_buckets, -np.inf)]
                     for sub in msubs}
        edges_j = jnp.asarray(edges)  # staging-ok: per-request agg input
        for seg, dseg, matched in seg_views:
            col = self._dev_numeric(dseg, field)
            if col is None:
                continue
            counts = np.asarray(agg_ops.bucketed_counts(
                col["values"], col["value_docs"], matched, edges_j,
                n_buckets_pad=n_pad_b))
            totals += counts[:n_buckets]
            for sub in msubs:
                sf, _ = self._field_type(sub, sub.type)
                scol = self._dev_numeric(dseg, sf)
                if scol is None:
                    continue
                b = jnp.searchsorted(edges_j, col["values"],
                                     side="right").astype(jnp.int32) - 1
                entry_ok = (matched[col["value_docs"]] & (b >= 0)
                            & (b < len(edges) - 1))
                entry_ok &= agg_ops._first_occurrence(col["value_docs"], b)
                per_doc = agg_ops.per_doc_partials(
                    scol["values"], scol["value_docs"], matched,
                    n_pad=dseg.n_pad)
                s, c, mn, mx = agg_ops.scatter_partials_to_buckets(
                    col["value_docs"], b, entry_ok, per_doc,
                    n_buckets_pad=n_pad_b)
                acc = sub_parts[sub.name]
                acc[0] += np.asarray(s)[:n_buckets]
                acc[1] += np.asarray(c)[:n_buckets]
                acc[2] = np.minimum(acc[2], np.asarray(mn)[:n_buckets])
                acc[3] = np.maximum(acc[3], np.asarray(mx)[:n_buckets])
        out = []
        for i in np.nonzero(totals)[0]:
            subs = {sub.name: _ser_tuple((float(sub_parts[sub.name][0][i]),
                                          int(sub_parts[sub.name][1][i]),
                                          float(sub_parts[sub.name][2][i]),
                                          float(sub_parts[sub.name][3][i])))
                    for sub in msubs}
            out.append([float(keys[i]), int(totals[i]), subs])
        return out

    # -- mask-composition buckets ----------------------------------------

    def _narrow(self, seg_views, mask_fn):
        """New seg_views with matched &= mask_fn(seg, dseg)."""
        out = []
        for seg, dseg, matched in seg_views:
            out.append((seg, dseg, matched & mask_fn(seg, dseg)))
        return out

    def _filter_mask_fn(self, query_json):
        from opensearch_tpu.search.compiler import compile_query
        from opensearch_tpu.search.executor import build_arrays
        from opensearch_tpu.search.plan import run_full
        from opensearch_tpu.search.query_dsl import parse_query

        plan, bind = compile_query(parse_query(query_json), self.ctx,
                                   scored=False)
        needed = plan.arrays()
        neg_inf = jnp.asarray(np.float32(-np.inf))  # staging-ok: per-request agg input

        def mask_fn(seg, dseg):
            A = build_arrays(dseg, needed, self.ctx.mapper,
                             live=self.ctx.live_jnp(seg, dseg))
            dims, ins = plan.prepare(bind, seg, dseg, self.ctx)
            _scores, matched = run_full(plan, dims, A, ins, neg_inf)
            return matched
        return mask_fn

    def _single_bucket(self, req, narrowed) -> dict:
        return {"t": "single",
                "doc_count": sum(int(m.sum()) for _s, _d, m in narrowed),
                "subs": {sub.name: self._part_one(sub, narrowed)
                         for sub in req.subs
                         if sub.type not in _PIPELINE_TYPES}}

    def _part_filter(self, req, seg_views) -> dict:
        return self._single_bucket(
            req, self._narrow(seg_views, self._filter_mask_fn(req.params)))

    def _part_filters(self, req, seg_views) -> dict:
        filters = req.params.get("filters")
        if not isinstance(filters, dict):
            raise ParsingError("[filters] aggregation requires keyed filters")
        buckets = {}
        for key, query_json in filters.items():
            narrowed = self._narrow(seg_views, self._filter_mask_fn(query_json))
            buckets[key] = self._single_bucket(req, narrowed)
        return {"t": "filters", "buckets": buckets}

    def _part_global(self, req, seg_views) -> dict:
        widened = [(seg, dseg, self.ctx.live_jnp(seg, dseg))
                   for seg, dseg, _m in seg_views]
        return self._single_bucket(req, widened)

    def _part_missing(self, req, seg_views) -> dict:
        field, ft = self._field_type(req, "missing")
        from opensearch_tpu.search.query_dsl import ExistsQuery
        from opensearch_tpu.search.compiler import compile_query
        from opensearch_tpu.search.executor import build_arrays
        from opensearch_tpu.search.plan import run_full

        plan, bind = compile_query(ExistsQuery(field=field), self.ctx,
                                   scored=False)
        needed = plan.arrays()
        neg_inf = jnp.asarray(np.float32(-np.inf))  # staging-ok: per-request agg input

        def mask_fn(seg, dseg):
            A = build_arrays(dseg, needed, self.ctx.mapper,
                             live=self.ctx.live_jnp(seg, dseg))
            dims, ins = plan.prepare(bind, seg, dseg, self.ctx)
            _s, exists = run_full(plan, dims, A, ins, neg_inf)
            return ~exists & self.ctx.live_jnp(seg, dseg)
        return self._single_bucket(req, self._narrow(seg_views, mask_fn))

    def _part_range(self, req, seg_views, is_date=False,
                    kind="numeric") -> dict:
        field, ft = self._field_type(req, "range")
        ranges = req.params.get("ranges")
        if not ranges:
            raise ParsingError("[range] aggregation requires [ranges]")

        def parse_bound(v):
            if v is None:
                return None
            if is_date:
                # the FIELD's parser honors format: epoch_second etc.
                return (ft.range_bound(v) if ft is not None
                        else parse_date_millis(v))
            if kind == "ip":
                from opensearch_tpu.mapping.types import parse_ip_long
                return parse_ip_long(v)
            return float(v)

        # buckets sort by (from asc, to asc) regardless of request
        # order (RangeAggregator's range sorting)
        def _order_key(r):
            f = parse_bound(r.get("from"))
            t = parse_bound(r.get("to"))
            return (-np.inf if f is None else f,
                    np.inf if t is None else t)
        ranges = sorted(ranges, key=_order_key)
        buckets = []
        for r in ranges:
            frm = r.get("from")
            to = r.get("to")
            frm_v = parse_bound(frm)
            to_v = parse_bound(to)
            inc_hi = bool(r.get("_to_inclusive", False))

            missing = req.params.get("missing")
            missing_v = parse_bound(missing) if missing is not None \
                else None
            lo_b = -np.inf if frm_v is None else frm_v
            hi_b = np.inf if to_v is None else to_v
            missing_in = (missing_v is not None and lo_b <= missing_v
                          and (missing_v <= hi_b if inc_hi
                               else missing_v < hi_b))

            def mask_fn(seg, dseg, frm_v=frm_v, to_v=to_v,
                        inc_hi=inc_hi, missing_in=missing_in):
                col = self._dev_numeric(dseg, field)
                if col is None:
                    if missing_in:      # every doc lacks the field
                        return jnp.ones(dseg.n_pad, bool)
                    return jnp.zeros(dseg.n_pad, bool)
                from opensearch_tpu.ops.filters import range_mask
                lo = -np.inf if frm_v is None else frm_v
                hi = np.inf if to_v is None else to_v
                vals = col["values"].astype(jnp.float64)
                hit = range_mask(vals, col["value_docs"], lo, hi,
                                 include_lo=True, include_hi=inc_hi,
                                 n_pad=dseg.n_pad)
                if missing_in:
                    # docs without a value take the [missing] value
                    hit = hit | ~col["exists"]
                return hit
            narrowed = self._narrow(seg_views, mask_fn)
            key = r.get("key")
            if key is None:
                def _bound(raw, parsed):
                    if raw is None:
                        return "*"
                    if is_date:
                        # numeric literals echo verbatim; date STRINGS
                        # render at millis precision
                        if isinstance(raw, str) and not str(
                                raw).lstrip("-").isdigit():
                            return format_date_millis(int(parsed))
                        return str(raw)
                    if kind == "ip":
                        return str(raw)
                    return str(float(parsed))
                key = _bound(frm, frm_v) + "-" + _bound(to, to_v)
            b = self._single_bucket(req, narrowed)
            b["key"] = key
            if frm is not None:
                b["from"] = frm if kind == "ip" else frm_v
            if to is not None:
                b["to"] = to if kind == "ip" else to_v
            buckets.append(b)
        return {"t": "ranges", "buckets": buckets}

    def _part_date_range(self, req, seg_views) -> dict:
        return self._part_range(req, seg_views, is_date=True)

    def _part_ip_range(self, req, seg_views) -> dict:
        """ip_range: from/to ip literals or CIDR masks over the monotone
        int64 ip column (bucket/range/IpRangeAggregationBuilder; a mask
        becomes an INCLUSIVE [network, broadcast] range)."""
        import ipaddress

        ranges = []
        for r in req.params.get("ranges") or []:
            if "mask" in r:
                net = ipaddress.ip_network(str(r["mask"]), strict=False)
                ranges.append({"key": r.get("key", str(r["mask"])),
                               "from": str(net.network_address),
                               "to": str(ipaddress.ip_address(
                                   int(net.broadcast_address) + 1))})
            else:
                ranges.append(dict(r))
        req2 = AggRequest(req.name, "ip_range",
                          {**req.params, "ranges": ranges}, req.subs)
        return self._part_range(req2, seg_views, kind="ip")


# ---------------------------------------------------------------------------
# Coordinator-side reduce (InternalAggregations.reduce analog) — pure
# function of the request + serialized partials; needs no segments, so it
# runs identically on a coordinating-only node.
# ---------------------------------------------------------------------------


def reduce_aggs(aggs_json: dict, partials: list[dict]) -> dict:
    reqs = parse_aggs(aggs_json)
    out = {r.name: _red_one(r, [p.get(r.name) for p in partials
                                if p is not None
                                and p.get(r.name) is not None])
           for r in reqs if r.type not in _PIPELINE_TYPES}
    # pipeline aggs run over the fully-reduced tree (the reference's
    # post-reduce PipelineAggregator pass)
    return _apply_pipelines(reqs, out)


def _red_one(req, parts: list):
    if req.type in ("min", "max", "sum", "avg", "value_count", "stats"):
        return _finish_metric(req.type,
                              _merge_tuples([p["v"] for p in parts]))
    fn = _REDUCERS.get(req.type)
    if fn is None:
        raise ParsingError(f"unknown aggregation type [{req.type}]")
    return fn(req, parts)


def _red_cardinality(req, parts):
    exact: set = set()
    hll = None
    threshold = min((p.get("thr", CARD_EXACT_MAX) for p in parts),
                    default=CARD_EXACT_MAX)
    for p in parts:
        if p["kind"] == "set":
            exact.update(_freeze(v) for v in p["v"])
        else:
            regs = np.asarray(p["regs"], np.uint8)
            hll = regs if hll is None else np.maximum(hll, regs)
    if hll is None and len(exact) <= threshold:
        return {"value": len(exact)}
    if exact:
        regs = _hll_from_values(exact)
        hll = regs if hll is None else np.maximum(hll, regs)
    return {"value": _hll_estimate(hll)}


def _freeze(v):
    return tuple(v) if isinstance(v, list) else v


def _red_percentiles(req, parts):
    percents = req.params.get("percents",
                              [1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0])
    vs, ws = [], []
    all_raw = True
    for p in parts:
        if p["kind"] == "raw":
            if p["v"]:
                vs.append(np.asarray(p["v"], np.float64))
                ws.append(np.ones(len(p["v"])))
        else:
            all_raw = False
            vs.append(np.asarray(p["m"], np.float64))
            ws.append(np.asarray(p["w"], np.float64))
    if not vs:
        return {"values": {f"{p}": None for p in percents}}
    v, w = np.concatenate(vs), np.concatenate(ws)
    if all_raw:
        return {"values": {f"{float(p)}": float(np.percentile(v, p))
                           for p in percents}}
    return {"values": {f"{float(p)}": _weighted_percentile(v, w, p)
                       for p in percents}}


def _red_extended_stats(req, parts):
    s, c, mn, mx = _merge_tuples([p["v"][:4] for p in parts])
    sq = sum(float(p["v"][4]) for p in parts)
    sigma = float(req.params.get("sigma", 2.0))
    if not c:
        return {"count": 0, "min": None, "max": None, "avg": None,
                "sum": 0.0, "sum_of_squares": None, "variance": None,
                "std_deviation": None,
                "std_deviation_bounds": {"upper": None, "lower": None}}
    avg = s / c
    var = sq / c - avg * avg
    std = float(np.sqrt(max(var, 0.0)))
    var_samp = (sq - c * avg * avg) / (c - 1) if c > 1 else None
    return {"count": int(c), "min": mn, "max": mx, "avg": avg, "sum": s,
            "sum_of_squares": sq, "variance": var,
            "variance_population": var, "variance_sampling": var_samp,
            "std_deviation": std, "std_deviation_population": std,
            "std_deviation_sampling": (float(np.sqrt(max(var_samp, 0.0)))
                                       if var_samp is not None else None),
            "std_deviation_bounds": {"upper": avg + sigma * std,
                                     "lower": avg - sigma * std}}


def _red_weighted_avg(req, parts):
    vw = sum(p["v"][0] for p in parts)
    w = sum(p["v"][1] for p in parts)
    return {"value": (vw / w) if w else None}


def _pct_values_weights(parts):
    vs, ws = [], []
    for p in parts:
        if p["kind"] == "raw":
            if p["v"]:
                vs.append(np.asarray(p["v"], np.float64))
                ws.append(np.ones(len(p["v"])))
        else:
            vs.append(np.asarray(p["m"], np.float64))
            ws.append(np.asarray(p["w"], np.float64))
    if not vs:
        return None, None
    return np.concatenate(vs), np.concatenate(ws)


def _red_percentile_ranks(req, parts):
    values = req.params.get("values") or []
    v, w = _pct_values_weights(parts)
    out = {}
    for x in values:
        if v is None:
            out[f"{float(x)}"] = None
        else:
            out[f"{float(x)}"] = float(
                100.0 * w[v <= float(x)].sum() / w.sum())
    return {"values": out}


def _red_mad(req, parts):
    v, w = _pct_values_weights(parts)
    if v is None:
        return {"value": None}
    med = _weighted_percentile(v, w, 50.0)
    return {"value": _weighted_percentile(np.abs(v - med), w, 50.0)}


def _red_top_hits(req, parts):
    size = int(req.params.get("size", 3))
    from_ = int(req.params.get("from", 0))
    sort_field, sort_desc = _top_hits_sort(req.params.get("sort"))
    hits = [h for p in parts for h in p["hits"]]
    if sort_field is None:
        hits.sort(key=lambda h: (h.get("_score") if h.get("_score")
                                 is not None else -np.inf), reverse=True)
    else:
        last = -np.inf if sort_desc else np.inf
        hits.sort(key=lambda h: (h["sort"][0] if h.get("sort")
                                 and h["sort"][0] is not None else last),
                  reverse=sort_desc)
    total = sum(p["total"] for p in parts)
    page = hits[from_: from_ + size]
    max_score = None
    scores = [h["_score"] for h in hits if h.get("_score") is not None]
    if scores:
        max_score = max(scores)
    return {"hits": {"total": {"value": int(total), "relation": "eq"},
                     "max_score": max_score, "hits": page}}


def _is_count_desc(order) -> bool:
    if isinstance(order, list):
        order = order[0] if order else {"_count": "desc"}
    ((what, direction),) = order.items()
    return what == "_count" and str(direction).lower() == "desc"


def _terms_order_key(order):
    if isinstance(order, list):
        order = order[0] if order else {"_count": "desc"}
    ((what, direction),) = order.items()
    desc = str(direction).lower() == "desc"
    if what == "_count":
        return lambda kv: ((-kv[1] if desc else kv[1]), kv[0])
    if what in ("_key", "_term"):
        # python can't negate strings: rely on sort stability via reverse
        import functools

        def cmp(a, b):
            if a[0] == b[0]:
                return 0
            lt = a[0] < b[0]
            if desc:
                lt = not lt
            return -1 if lt else 1
        return functools.cmp_to_key(cmp)
    raise IllegalArgumentError(f"terms order [{what}] is not supported")


def _term_key(key, tn, dk):
    if tn == "boolean":
        return int(key)
    if dk == "long":
        return int(key)
    if dk == "double":
        return float(key)
    return key


def _term_key_as_string(key, tn):
    if tn == "boolean":
        return "true" if key else "false"
    if tn == "date":
        return format_date_millis(int(key))
    return None


def _red_terms(req, parts):
    size = int(req.params.get("size", 10))
    min_doc_count = int(req.params.get("min_doc_count", 1))
    order = req.params.get("order", {"_count": "desc"})
    tn = dk = None
    merged: dict = {}
    sub_parts: dict = {}
    keys_of: list[set] = []
    for p in parts:
        if p.get("tn") is not None:
            tn, dk = p["tn"], p["dk"]
        seen = set()
        for key, count, subs in p["buckets"]:
            if isinstance(key, float) and dk == "long":
                key = int(key)      # JSON round-trip may floatify longs
            seen.add(key)
            merged[key] = merged.get(key, 0) + count
            for sname, tup in subs.items():
                if isinstance(tup, dict):      # top_hits partial
                    sub_parts.setdefault((sname, key), []).append(tup)
                    continue
                prev = sub_parts.get((sname, key))
                sub_parts[(sname, key)] = (
                    _ser_tuple(_merge_tuples([prev, tup]))
                    if prev is not None else tup)
        keys_of.append(seen)
    if tn is None:
        return {"doc_count_error_upper_bound": 0, "sum_other_doc_count": 0,
                "buckets": []}
    inc, exc = req.params.get("include"), req.params.get("exclude")
    if inc is not None or exc is not None:
        sel = _terms_include_filter(inc, exc, tn)
        merged = {k: c for k, c in merged.items() if sel(k)}
    items = [(k, c) for k, c in merged.items() if c >= min_doc_count]
    items.sort(key=_terms_order_key(order))
    total_in_buckets = sum(c for _k, c in items)
    items = items[:size]
    error = 0
    buckets = []
    for key, count in items:
        # a shard that truncated its list and omitted this key may hold up
        # to its min_inc more docs for it (the reference's per-bucket
        # doc_count_error derivation)
        err = sum(p["min_inc"] for p, seen in zip(parts, keys_of)
                  if key not in seen)
        error = max(error, err)
        b = {"key": _term_key(key, tn, dk), "doc_count": int(count)}
        kas = _term_key_as_string(key, tn)
        if kas is not None:
            b["key_as_string"] = kas
        for sub in _metric_subs(req):
            tup = sub_parts.get((sub.name, key))
            b[sub.name] = _finish_metric(
                sub.type, _merge_tuples([tup]) if tup is not None
                else (0.0, 0, np.inf, -np.inf))
        for sub in _top_hits_subs(req):
            b[sub.name] = _red_top_hits(
                sub, sub_parts.get((sub.name, key), []))
        buckets.append(b)
    sum_other = (total_in_buckets - sum(b["doc_count"] for b in buckets)
                 + sum(p["others"] for p in parts))
    return {"doc_count_error_upper_bound": int(error),
            "sum_other_doc_count": int(sum_other),
            "buckets": buckets}


def _mix64(v: int) -> int:
    """BitMixer.mix64 (Stafford variant 9, libs/common BitMixer.java:120)
    — signed, for floorMod parity with the reference's partitioning."""
    m = (1 << 64) - 1
    z = v & m
    z = ((z ^ (z >> 32)) * 0x4CD6944C5CC20B6D) & m
    z = ((z ^ (z >> 29)) * 0xFC12C5B19D3259E9) & m
    z ^= z >> 32
    return z - (1 << 64) if z >= (1 << 63) else z


def _terms_include_filter(inc, exc, tn):
    """terms include/exclude: exact-value arrays, a regex string, or the
    partition form {partition, num_partitions} — hash-compatible with
    the reference (IncludeExclude.java:239 murmur3_x86_32 seed 31 +
    floorMod for strings; Long.hashCode for numerics)."""
    if isinstance(inc, dict):
        part = int(inc.get("partition", -1))
        num = int(inc.get("num_partitions", 0))
        if part < 0 or num <= 0 or part >= num:
            raise IllegalArgumentError(
                "Missing or invalid [partition]/[num_partitions] for "
                "partition-based include")
        if exc is not None:
            raise IllegalArgumentError(
                "Cannot specify any excludes when using a "
                "partition-based include")
        from opensearch_tpu.indices.service import murmur3_32

        def sel(key):
            if isinstance(key, str):
                h = murmur3_32(key.encode("utf-8"), 31)
                if h >= 2**31:
                    h -= 2**32
            else:
                h = _mix64(int(key))       # BitMixer.mix64 (long keys)
            return h % num == part
        return sel
    def norm(vals):
        out = set()
        for v in vals:
            out.add(v)
            out.add(str(v))
            if tn == "date":
                try:
                    out.add(parse_date_millis(v))
                except (ValueError, IllegalArgumentError, TypeError):
                    pass
        return out

    def key_forms(key):
        forms = {key, str(key)}
        kas = _term_key_as_string(key, tn)
        if kas is not None:
            forms.add(kas)
        return forms

    def matches(spec, key):
        if spec is None:
            return None
        if isinstance(spec, str):            # regex form
            return any(re.fullmatch(spec, str(f)) for f in key_forms(key))
        vals = norm(spec if isinstance(spec, list) else [spec])
        return bool(key_forms(key) & vals)

    def sel(key):
        if inc is not None and not matches(inc, key):
            return False
        if exc is not None and matches(exc, key):
            return False
        return True
    return sel


def _dh_offset(req) -> int:
    offset = req.params.get("offset", 0)
    if isinstance(offset, str) and offset:
        offset = _parse_duration_ms(offset.lstrip("+-")) * (
            -1 if offset.startswith("-") else 1)
    return int(offset)


def _red_histogram(req, parts, is_date=False):
    min_doc_count = int(req.params.get("min_doc_count", 0))
    mns = [p["mn"] for p in parts if p["mn"] is not None]
    mxs = [p["mx"] for p in parts if p["mx"] is not None]
    if not mns:
        return {"buckets": []}
    mn, mx = min(mns), max(mxs)
    if is_date:
        calendar = req.params.get("calendar_interval")
        fixed = req.params.get("fixed_interval") or req.params.get("interval")
        if calendar is None and fixed is None:
            raise ParsingError(
                "date_histogram requires calendar_interval or fixed_interval")
        edges = build_date_edges(int(mn), int(mx), calendar=calendar,
                                 fixed=None if calendar else fixed,
                                 offset=_dh_offset(req))
        keys = edges[:-1].astype(np.int64)
        fmt = req.params.get("format") or ""
    else:
        interval = float(req.params["interval"])
        if interval <= 0:
            raise IllegalArgumentError("[interval] must be > 0")
        offset = float(req.params.get("offset", 0))
        first = np.floor((mn - offset) / interval) * interval + offset
        n = int((mx - first) // interval) + 2
        if n > MAX_BUCKETS:
            raise IllegalArgumentError(
                f"trying to create too many buckets ({n} > {MAX_BUCKETS})")
        keys = (first + interval * np.arange(n - 1, dtype=np.float64))
        fmt = None
    # merge shard buckets onto the global grid; float keys land exactly on
    # grid points (same rounding arithmetic shard-side), so match by
    # nearest-grid-index rather than float equality
    counts = np.zeros(len(keys), np.int64)
    subs_acc: dict = {}
    for p in parts:
        for key, count, subs in p["buckets"]:
            if is_date:
                i = int(np.searchsorted(keys, int(round(key))))
                if i >= len(keys) or keys[i] != int(round(key)):
                    i = max(0, i - 1)
            else:
                i = min(max(int(round((key - keys[0]) / interval)), 0),
                        len(keys) - 1)
            counts[i] += count
            for sname, tup in subs.items():
                prev = subs_acc.get((sname, i))
                subs_acc[(sname, i)] = (
                    _ser_tuple(_merge_tuples([prev, tup]))
                    if prev is not None else tup)
    buckets = []
    for i, key in enumerate(keys):
        if counts[i] < min_doc_count:
            continue
        b = {"key": int(key) if is_date else float(key),
             "doc_count": int(counts[i])}
        if is_date:
            b["key_as_string"] = _fmt_date(int(key), fmt or None)
        for sub in _metric_subs(req):
            tup = subs_acc.get((sub.name, i))
            b[sub.name] = _finish_metric(
                sub.type, _merge_tuples([tup]) if tup is not None
                else (0.0, 0, np.inf, -np.inf))
        buckets.append(b)
    return {"buckets": buckets}


def _composite_sources(req):
    """[(name, field, value_transform, order, kind)] for a composite
    request's sources."""
    import math as _math

    sources = req.params.get("sources")
    if not isinstance(sources, list) or not sources:
        raise ParsingError("Required [sources]")
    out = []
    for s in sources:
        if not isinstance(s, dict) or len(s) != 1:
            raise ParsingError("[composite] source must have one name")
        ((name, body),) = s.items()
        if not isinstance(body, dict) or len(body) != 1:
            raise ParsingError(
                f"[composite] source [{name}] must have one type")
        ((styp, cfg),) = body.items()
        field = cfg.get("field")
        if not field:
            raise ParsingError(f"[composite] source [{name}] requires "
                               "[field]")
        order = cfg.get("order", "asc")
        if styp == "terms":
            xform, kind = None, "terms"
        elif styp == "histogram":
            interval = float(cfg.get("interval", 0))
            if interval <= 0:
                raise ParsingError("[interval] must be > 0")
            xform = lambda v, i=interval: _math.floor(float(v) / i) * i  # noqa: E731
            kind = "histogram"
        elif styp == "date_histogram":
            calendar = cfg.get("calendar_interval")
            if calendar in ("month", "1M"):
                def xform(v):
                    dt = _dt.datetime.fromtimestamp(
                        int(v) / 1000, tz=_dt.timezone.utc)
                    return int(_floor_month(dt, 1).timestamp() * 1000)
            elif calendar in ("year", "1y"):
                def xform(v):
                    dt = _dt.datetime.fromtimestamp(
                        int(v) / 1000, tz=_dt.timezone.utc)
                    return int(_dt.datetime(
                        dt.year, 1, 1,
                        tzinfo=_dt.timezone.utc).timestamp() * 1000)
            else:
                fixed = cfg.get("fixed_interval") or cfg.get("interval")
                ms = _CAL_FIXED_MS.get(calendar)
                if ms is None:
                    if fixed is None:
                        raise ParsingError(
                            f"[composite] source [{name}] requires an "
                            "interval")
                    ms = _parse_duration_ms(fixed)
                off = cfg.get("offset", 0)
                if isinstance(off, str) and off:
                    off = (_parse_duration_ms(off.lstrip("+-"))
                           * (-1 if off.startswith("-") else 1))
                off = int(off)
                xform = (lambda v, m=ms, o=off:
                         ((int(v) - o) // m) * m + o)  # noqa: E731
            kind = "date"
        else:
            raise ParsingError(
                f"[composite] source type [{styp}] is not supported")
        out.append((name, field, xform, order, kind,
                    cfg.get("format")))
    return out


def _composite_sort_key(sources):
    """Comparable wrapper honoring each source's asc/desc order."""
    import functools

    orders = [s[3] for s in sources]

    def cmp(a, b):
        for av, bv, o in zip(a, b, orders):
            if av == bv:
                continue
            lt = av < bv
            if str(o).lower() == "desc":
                lt = not lt
            return -1 if lt else 1
        return 0

    return functools.cmp_to_key(cmp)


def _red_composite(req, parts):
    sources = _composite_sources(req)
    size = int(req.params.get("size", 10))
    merged: dict = {}
    sub_parts: dict = {}
    for p in parts:
        for key, count, subs in p["buckets"]:
            key = tuple(int(v) if s[4] == "date"
                        else (float(v) if s[4] == "histogram" else v)
                        for v, s in zip(key, sources))
            merged[key] = merged.get(key, 0) + count
            for sname, tup in subs.items():
                prev = sub_parts.get((sname, key))
                sub_parts[(sname, key)] = (
                    _ser_tuple(_merge_tuples([prev, tup]))
                    if prev is not None else tup)
    K = _composite_sort_key(sources)
    items = sorted(merged.items(), key=lambda kv: K(kv[0]))[:size]
    buckets = []
    for key, count in items:
        rendered = {}
        for v, s in zip(key, sources):
            name, kind, fmt = s[0], s[4], s[5]
            if kind == "date" and fmt:
                v = _fmt_date(int(v), fmt)
            rendered[name] = v
        b = {"key": rendered, "doc_count": int(count)}
        for sub in _metric_subs(req):
            tup = sub_parts.get((sub.name, key))
            b[sub.name] = _finish_metric(
                sub.type, _merge_tuples([tup]) if tup is not None
                else (0.0, 0, np.inf, -np.inf))
        buckets.append(b)
    out = {"buckets": buckets}
    if buckets:
        out["after_key"] = buckets[-1]["key"]
    return out


def _jlh(fg: int, fg_total: int, bg: int, bg_total: int) -> float:
    """JLH significance: (fg% - bg%) * (fg% / bg%) — the reference's
    default heuristic (bucket/terms/heuristic/JLHScore.java:103)."""
    if not fg_total or not bg_total or not bg:
        return 0.0
    fg_rate = fg / fg_total
    bg_rate = bg / bg_total
    if fg_rate <= bg_rate:
        return 0.0
    return (fg_rate - bg_rate) * (fg_rate / bg_rate)


def _red_significant_terms(req, parts):
    size = int(req.params.get("size", 10))
    min_doc_count = int(req.params.get("min_doc_count", 3))
    tn = dk = None
    fg_total = bg_total = 0
    fg: dict = {}
    bg: dict = {}
    for p in parts:
        if p.get("tn") is not None:
            tn, dk = p["tn"], p["dk"]
        fg_total += p["fg_total"]
        bg_total += p["bg_total"]
        for key, f, b in p["buckets"]:
            if isinstance(key, float) and dk == "long":
                key = int(key)
            fg[key] = fg.get(key, 0) + f
            bg[key] = bg.get(key, 0) + b
    scored = []
    for key, f in fg.items():
        if f < min_doc_count:
            continue
        score = _jlh(f, fg_total, bg[key], bg_total)
        if score > 0:
            scored.append((score, key, f, bg[key]))
    scored.sort(key=lambda r: (-r[0], r[1]))
    buckets = [{"key": _term_key(key, tn, dk), "doc_count": int(f),
                "score": score, "bg_count": int(b)}
               for score, key, f, b in scored[:size]]
    return {"doc_count": int(fg_total), "bg_count": int(bg_total),
            "buckets": buckets}


def _red_rare_terms(req, parts):
    max_dc = int(req.params.get("max_doc_count", 1))
    tn = dk = None
    counts: dict = {}
    over: set = set()
    for p in parts:
        if p.get("tn") is not None:
            tn, dk = p["tn"], p["dk"]
        over.update(_freeze(t) for t in p.get("over", []))
        for key, c in p["buckets"]:
            if isinstance(key, float) and dk == "long":
                key = int(key)
            counts[key] = counts.get(key, 0) + c
    items = [(k, c) for k, c in counts.items()
             if c <= max_dc and k not in over]
    items.sort(key=lambda kv: kv[0])
    return {"buckets": [{"key": _term_key(k, tn, dk), "doc_count": int(c)}
                        for k, c in items]}


def _red_multi_terms(req, parts):
    size = int(req.params.get("size", 10))
    min_doc_count = int(req.params.get("min_doc_count", 1))
    order = req.params.get("order", {"_count": "desc"})
    merged: dict = {}
    sub_parts: dict = {}
    keys_of: list[set] = []
    for p in parts:
        seen = set()
        for key, count, subs in p["buckets"]:
            key = tuple(key)
            seen.add(key)
            merged[key] = merged.get(key, 0) + count
            for sname, tup in subs.items():
                prev = sub_parts.get((sname, key))
                sub_parts[(sname, key)] = (
                    _ser_tuple(_merge_tuples([prev, tup]))
                    if prev is not None else tup)
        keys_of.append(seen)
    items = [(k, c) for k, c in merged.items() if c >= min_doc_count]
    items.sort(key=_terms_order_key(order))
    total_in_buckets = sum(c for _k, c in items)
    items = items[:size]
    buckets = []
    error = 0
    for key, count in items:
        err = sum(p["min_inc"] for p, seen in zip(parts, keys_of)
                  if key not in seen)
        error = max(error, err)
        b = {"key": list(key),
             "key_as_string": "|".join(str(k) for k in key),
             "doc_count": int(count)}
        for sub in _metric_subs(req):
            tup = sub_parts.get((sub.name, key))
            b[sub.name] = _finish_metric(
                sub.type, _merge_tuples([tup]) if tup is not None
                else (0.0, 0, np.inf, -np.inf))
        buckets.append(b)
    sum_other = (total_in_buckets - sum(b["doc_count"] for b in buckets)
                 + sum(p["others"] for p in parts))
    return {"doc_count_error_upper_bound": int(error),
            "sum_other_doc_count": int(sum_other),
            "buckets": buckets}


def _red_single(req, parts):
    out = {"doc_count": sum(p["doc_count"] for p in parts)}
    for sub in req.subs:
        if sub.type in _PIPELINE_TYPES:
            continue                    # applied in the post-reduce pass
        out[sub.name] = _red_one(sub, [p["subs"][sub.name] for p in parts
                                       if sub.name in p.get("subs", {})])
    return out


def _red_filters(req, parts):
    keys = []
    for p in parts:
        for k in p["buckets"]:
            if k not in keys:
                keys.append(k)
    buckets = {}
    for k in keys:
        kparts = [p["buckets"][k] for p in parts if k in p["buckets"]]
        buckets[k] = _red_single(req, kparts)
    return {"buckets": buckets}


def _red_ranges(req, parts):
    if not parts:
        return {"buckets": []}
    n = len(parts[0]["buckets"])
    buckets = []
    for i in range(n):
        slot = [p["buckets"][i] for p in parts]
        b = _red_single(req, slot)
        proto = slot[0]
        b["key"] = proto["key"]
        if "from" in proto:
            b["from"] = proto["from"]
        if "to" in proto:
            b["to"] = proto["to"]
        buckets.append(b)
    return {"buckets": buckets}


_REDUCERS = {
    "cardinality": _red_cardinality,
    "percentiles": _red_percentiles,
    "percentile_ranks": _red_percentile_ranks,
    "median_absolute_deviation": _red_mad,
    "extended_stats": _red_extended_stats,
    "weighted_avg": _red_weighted_avg,
    "top_hits": _red_top_hits,
    "terms": _red_terms,
    "significant_terms": _red_significant_terms,
    "rare_terms": _red_rare_terms,
    "multi_terms": _red_multi_terms,
    "composite": _red_composite,
    "histogram": lambda req, parts: _red_histogram(req, parts, is_date=False),
    "date_histogram": lambda req, parts: _red_histogram(req, parts,
                                                        is_date=True),
    "filter": _red_single,
    "filters": _red_filters,
    "global": _red_single,
    "missing": _red_single,
    "range": _red_ranges,
    "date_range": _red_ranges,
    "ip_range": _red_ranges,
}
