"""Aggregations: request parsing, per-segment collection, cross-segment
reduce, response formatting.

Analog of the reference's two-phase model (per-shard collect via
``BucketCollector`` -> coordinator ``InternalAggregations.reduce``; ref
search/aggregations/BucketCollector.java:46,
bucket/histogram/DateHistogramAggregator.java,
bucket/terms/GlobalOrdinalsStringTermsAggregator.java).  Collection is
array-oriented: bucket counts and metric partials are scatter-adds over
doc-value columns (ops/aggs.py); the reduce merges per-segment partials on
host exactly like the coordinator reduce merges per-shard ones — so the
same code path later serves the cross-shard merge.

Composition model: every bucket agg that selects a doc subset (filter,
filters, range, missing, global) recurses with a narrowed matched mask, so
arbitrary nesting works; terms/histogram support metric sub-aggs computed
in the same pass via two-level scatters.
"""

from __future__ import annotations

import datetime as _dt
import re
from dataclasses import dataclass, field as dc_field

import numpy as np

import opensearch_tpu.common.jaxenv  # noqa: F401
import jax.numpy as jnp

from opensearch_tpu.common.errors import IllegalArgumentError, ParsingError
from opensearch_tpu.index.segment import pad_pow2
from opensearch_tpu.mapping.types import format_date_millis, parse_date_millis
from opensearch_tpu.ops import aggs as agg_ops

MAX_BUCKETS = 65536          # search.max_buckets default
_METRIC_TYPES = {"min", "max", "sum", "avg", "value_count", "stats",
                 "cardinality", "percentiles"}
_BUCKET_TYPES = {"terms", "histogram", "date_histogram", "range",
                 "date_range", "filter", "filters", "global", "missing"}


@dataclass
class AggRequest:
    name: str
    type: str
    params: dict
    subs: list = dc_field(default_factory=list)


def parse_aggs(aggs_json: dict) -> list[AggRequest]:
    out = []
    for name, body in (aggs_json or {}).items():
        subs_json = body.get("aggs") or body.get("aggregations") or {}
        types = [k for k in body if k not in ("aggs", "aggregations", "meta")]
        if len(types) != 1:
            raise ParsingError(
                f"aggregation [{name}] must have exactly one type, got {types}")
        typ = types[0]
        if typ not in _METRIC_TYPES | _BUCKET_TYPES:
            raise ParsingError(f"unknown aggregation type [{typ}]")
        subs = parse_aggs(subs_json)
        if typ in _METRIC_TYPES and subs:
            raise ParsingError(
                f"metric aggregation [{name}] cannot have sub-aggregations")
        out.append(AggRequest(name, typ, body[typ], subs))
    return out


_DURATION = re.compile(r"^(\d+)(ms|s|m|h|d)$")
_DUR_MS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000, "d": 86_400_000}
_CAL_FIXED_MS = {"second": 1000, "1s": 1000, "minute": 60_000, "1m": 60_000,
                 "hour": 3_600_000, "1h": 3_600_000, "day": 86_400_000,
                 "1d": 86_400_000, "week": 7 * 86_400_000, "1w": 7 * 86_400_000}


def _parse_duration_ms(s: str) -> int:
    m = _DURATION.match(str(s))
    if not m:
        raise IllegalArgumentError(f"failed to parse interval [{s}]")
    return int(m.group(1)) * _DUR_MS[m.group(2)]


def _floor_month(dt: _dt.datetime, months: int) -> _dt.datetime:
    total = dt.year * 12 + (dt.month - 1)
    total = (total // months) * months
    return _dt.datetime(total // 12, total % 12 + 1, 1, tzinfo=_dt.timezone.utc)


def _add_months(dt: _dt.datetime, months: int) -> _dt.datetime:
    total = dt.year * 12 + (dt.month - 1) + months
    return _dt.datetime(total // 12, total % 12 + 1, 1, tzinfo=_dt.timezone.utc)


def build_date_edges(lo: int, hi: int, calendar=None, fixed=None,
                     offset: int = 0) -> np.ndarray:
    """Ascending bucket edges (epoch millis) covering [lo, hi], aligned to
    the interval (Rounding.java analog, UTC only)."""
    if calendar in ("month", "1M", "quarter", "1q", "year", "1y"):
        months = {"month": 1, "1M": 1, "quarter": 3, "1q": 3,
                  "year": 12, "1y": 12}[calendar]
        start = _floor_month(
            _dt.datetime.fromtimestamp(lo / 1000, tz=_dt.timezone.utc), months)
        edges = [start]
        while edges[-1].timestamp() * 1000 <= hi:
            edges.append(_add_months(edges[-1], months))
        arr = np.asarray([int(e.timestamp() * 1000) for e in edges],
                         dtype=np.int64)
    else:
        if calendar is not None:
            ms = _CAL_FIXED_MS.get(calendar)
            if ms is None:
                raise IllegalArgumentError(
                    f"unknown calendar_interval [{calendar}]")
        else:
            ms = _parse_duration_ms(fixed)
        if calendar in ("week", "1w"):
            offset = (offset + 4 * 86_400_000) % ms   # epoch was a Thursday
        first = (lo - offset) // ms * ms + offset
        if first > lo:
            first -= ms
        n = (hi - first) // ms + 2
        if n > MAX_BUCKETS:
            raise IllegalArgumentError(
                f"trying to create too many buckets ({n} > {MAX_BUCKETS})")
        arr = first + ms * np.arange(n, dtype=np.int64)
    if len(arr) - 1 > MAX_BUCKETS:
        raise IllegalArgumentError(
            f"trying to create too many buckets ({len(arr) - 1} > {MAX_BUCKETS})")
    return arr


def _fmt_date(millis: int, fmt: str | None) -> str:
    if not fmt:
        return format_date_millis(int(millis))
    py = (fmt.replace("yyyy", "%Y").replace("MM", "%m").replace("dd", "%d")
          .replace("HH", "%H").replace("mm", "%M").replace("ss", "%S"))
    dt = _dt.datetime.fromtimestamp(millis / 1000, tz=_dt.timezone.utc)
    return dt.strftime(py)


class AggregationExecutor:
    """Runs an agg tree over per-segment matched masks.

    ``seg_views`` is [(seg, dseg, matched_jnp)] — the query phase's
    matched masks, one per segment.
    """

    def __init__(self, ctx):
        self.ctx = ctx               # compiler.ShardContext

    def run(self, aggs_json: dict, seg_views: list) -> dict:
        reqs = parse_aggs(aggs_json)
        return {r.name: self._run_one(r, seg_views) for r in reqs}

    # -- helpers ----------------------------------------------------------

    def _field_type(self, req, caller):
        field = req.params.get("field")
        if field is None:
            raise ParsingError(f"[{caller}] aggregation requires a [field]")
        ft = self.ctx.field_type(field)
        if ft is not None and ft.dv_kind == "none":
            raise IllegalArgumentError(
                f"Text fields are not optimised for operations that require "
                f"per-document field data like aggregations and sorting, so "
                f"these operations are disabled by default. Please use a "
                f"keyword field instead. Alternatively, set fielddata=true "
                f"on [{field}]")
        return field, ft

    def _numeric_column(self, seg, field):
        return seg.numeric_dv.get(field)

    def _dev_numeric(self, dseg, field):
        return dseg.numeric.get(field)

    # -- dispatch ---------------------------------------------------------

    def _run_one(self, req, seg_views):
        fn = getattr(self, f"_agg_{req.type}", None)
        if fn is None:
            raise ParsingError(f"unknown aggregation type [{req.type}]")
        return fn(req, seg_views)

    # -- metrics ----------------------------------------------------------

    def _collect_metric_partials(self, field, seg_views):
        s = 0.0
        c = 0
        mn, mx = np.inf, -np.inf
        for seg, dseg, matched in seg_views:
            col = self._dev_numeric(dseg, field)
            if col is None:
                continue
            ss, cc, mnn, mxx = agg_ops.masked_metrics(
                col["values"], col["value_docs"], matched)
            s += float(ss)
            c += int(cc)
            mn = min(mn, float(mnn))
            mx = max(mx, float(mxx))
        return s, c, mn, mx

    def _agg_min(self, req, seg_views):
        field, _ = self._field_type(req, "min")
        s, c, mn, mx = self._collect_metric_partials(field, seg_views)
        return {"value": mn if c else None}

    def _agg_max(self, req, seg_views):
        field, _ = self._field_type(req, "max")
        s, c, mn, mx = self._collect_metric_partials(field, seg_views)
        return {"value": mx if c else None}

    def _agg_sum(self, req, seg_views):
        field, _ = self._field_type(req, "sum")
        s, c, mn, mx = self._collect_metric_partials(field, seg_views)
        return {"value": s}

    def _agg_avg(self, req, seg_views):
        field, _ = self._field_type(req, "avg")
        s, c, mn, mx = self._collect_metric_partials(field, seg_views)
        return {"value": (s / c) if c else None}

    def _agg_value_count(self, req, seg_views):
        field, ft = self._field_type(req, "value_count")
        if ft is not None and ft.dv_kind == "ordinal":
            total = 0
            for seg, dseg, matched in seg_views:
                col = dseg.ordinal.get(field)
                if col is None:
                    continue
                ok = matched[col["value_docs"]] & (col["ords"] >= 0)
                total += int(ok.sum())
            return {"value": total}
        s, c, mn, mx = self._collect_metric_partials(field, seg_views)
        return {"value": c}

    def _agg_stats(self, req, seg_views):
        field, _ = self._field_type(req, "stats")
        s, c, mn, mx = self._collect_metric_partials(field, seg_views)
        return {"count": c, "min": mn if c else None, "max": mx if c else None,
                "avg": (s / c) if c else None, "sum": s}

    def _agg_cardinality(self, req, seg_views):
        """Exact distinct count (the reference's HLL++ is approximate; we
        can afford exact via per-segment term/value sets)."""
        field, ft = self._field_type(req, "cardinality")
        distinct = set()
        for seg, dseg, matched in seg_views:
            m = np.asarray(matched)
            if ft is not None and ft.dv_kind == "ordinal":
                dv = seg.ordinal_dv.get(field)
                if dv is None:
                    continue
                ok = m[dv.value_docs] if len(dv.value_docs) else np.zeros(0, bool)
                for o in np.unique(dv.ords[ok]):
                    distinct.add(dv.ord_terms[o])
            else:
                dv = seg.numeric_dv.get(field)
                if dv is None:
                    continue
                ok = m[dv.value_docs] if len(dv.value_docs) else np.zeros(0, bool)
                distinct.update(np.unique(dv.values[ok]).tolist())
        return {"value": len(distinct)}

    def _agg_percentiles(self, req, seg_views):
        field, _ = self._field_type(req, "percentiles")
        percents = req.params.get("percents",
                                  [1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0])
        chunks = []
        for seg, dseg, matched in seg_views:
            dv = seg.numeric_dv.get(field)
            if dv is None or not len(dv.value_docs):
                continue
            ok = np.asarray(matched)[dv.value_docs]
            chunks.append(dv.values[ok].astype(np.float64))
        if not chunks:
            return {"values": {f"{p}": None for p in percents}}
        allv = np.concatenate(chunks)
        return {"values": {f"{float(p)}": float(np.percentile(allv, p))
                           for p in percents}}

    # -- terms ------------------------------------------------------------

    def _agg_terms(self, req, seg_views):
        field, ft = self._field_type(req, "terms")
        size = int(req.params.get("size", 10))
        min_doc_count = int(req.params.get("min_doc_count", 1))
        order = req.params.get("order", {"_count": "desc"})
        if ft is None:
            return {"doc_count_error_upper_bound": 0, "sum_other_doc_count": 0,
                    "buckets": []}
        if ft.dv_kind == "ordinal":
            merged, sub_parts = self._terms_ordinal(field, seg_views, req.subs)
        else:
            merged, sub_parts = self._terms_numeric(field, seg_views, req.subs)

        items = [(k, c) for k, c in merged.items() if c >= min_doc_count]
        items.sort(key=self._terms_order_key(order))
        total_in_buckets = sum(c for _k, c in items)
        items = items[:size]
        buckets = []
        for key, count in items:
            b = {"key": self._term_key(key, ft), "doc_count": int(count)}
            kas = self._term_key_as_string(key, ft)
            if kas is not None:
                b["key_as_string"] = kas
            for sub in req.subs:
                b[sub.name] = self._finish_sub_metric(sub, sub_parts.get(
                    (sub.name, key), (0.0, 0, np.inf, -np.inf)))
            buckets.append(b)
        return {"doc_count_error_upper_bound": 0,
                "sum_other_doc_count": int(total_in_buckets
                                           - sum(b["doc_count"] for b in buckets)),
                "buckets": buckets}

    @staticmethod
    def _terms_order_key(order):
        if isinstance(order, list):
            order = order[0] if order else {"_count": "desc"}
        ((what, direction),) = order.items()
        desc = str(direction).lower() == "desc"
        if what == "_count":
            return lambda kv: ((-kv[1] if desc else kv[1]), kv[0])
        if what in ("_key", "_term"):
            # python can't negate strings: rely on sort stability via reverse
            import functools

            def cmp(a, b):
                if a[0] == b[0]:
                    return 0
                lt = a[0] < b[0]
                if desc:
                    lt = not lt
                return -1 if lt else 1
            return functools.cmp_to_key(cmp)
        raise IllegalArgumentError(f"terms order [{what}] is not supported")

    @staticmethod
    def _term_key(key, ft):
        if ft.type_name == "boolean":
            return int(key)
        if ft.dv_kind == "long":
            return int(key)
        if ft.dv_kind == "double":
            return float(key)
        return key

    @staticmethod
    def _term_key_as_string(key, ft):
        if ft.type_name == "boolean":
            return "true" if key else "false"
        if ft.type_name == "date":
            return format_date_millis(int(key))
        return None

    def _terms_ordinal(self, field, seg_views, subs):
        merged: dict = {}
        sub_parts: dict = {}
        for seg, dseg, matched in seg_views:
            dv = seg.ordinal_dv.get(field)
            col = dseg.ordinal.get(field)
            if dv is None or col is None:
                continue
            n_pad_b = pad_pow2(len(dv.ord_terms) + 1)
            counts = np.asarray(agg_ops.ordinal_counts(
                col["ords"], col["value_docs"], matched,
                n_buckets_pad=n_pad_b))
            nz = np.nonzero(counts[: len(dv.ord_terms)])[0]
            for o in nz:
                term = dv.ord_terms[o]
                merged[term] = merged.get(term, 0) + int(counts[o])
            for sub in subs:
                sf, sft = self._field_type(sub, sub.type)
                scol = self._dev_numeric(dseg, sf)
                if scol is None:
                    continue
                entry_ok = matched[col["value_docs"]] & (col["ords"] >= 0)
                per_doc = agg_ops.per_doc_partials(
                    scol["values"], scol["value_docs"], matched,
                    n_pad=dseg.n_pad)
                s, c, mn, mx = agg_ops.scatter_partials_to_buckets(
                    col["value_docs"], col["ords"], entry_ok, per_doc,
                    n_buckets_pad=n_pad_b)
                s, c = np.asarray(s), np.asarray(c)
                mn, mx = np.asarray(mn), np.asarray(mx)
                for o in nz:
                    term = dv.ord_terms[o]
                    key = (sub.name, term)
                    ps, pc, pmn, pmx = sub_parts.get(key,
                                                     (0.0, 0, np.inf, -np.inf))
                    sub_parts[key] = (ps + float(s[o]), pc + int(c[o]),
                                      min(pmn, float(mn[o])),
                                      max(pmx, float(mx[o])))
        return merged, sub_parts

    def _terms_numeric(self, field, seg_views, subs):
        merged: dict = {}
        sub_parts: dict = {}
        for seg, dseg, matched in seg_views:
            dv = seg.numeric_dv.get(field)
            if dv is None or not len(dv.value_docs):
                continue
            m = np.asarray(matched)
            ok = m[dv.value_docs]
            vals, docs = dv.values[ok], dv.value_docs[ok]
            # docs count once per distinct value; keep the native dtype for
            # the dedup — a float64 cast would collapse longs above 2^53
            pair_dtype = np.int64 if dv.kind == "long" else np.float64
            pairs = np.unique(np.stack([vals.astype(pair_dtype),
                                        docs.astype(pair_dtype)]), axis=1)
            uniq_vals, counts = np.unique(pairs[0], return_counts=True)
            for v, c in zip(uniq_vals, counts):
                key = float(v) if dv.kind == "double" else int(v)
                merged[key] = merged.get(key, 0) + int(c)
            for sub in subs:
                sf, _sft = self._field_type(sub, sub.type)
                sdv = seg.numeric_dv.get(sf)
                if sdv is None:
                    continue
                per_doc_sum = np.zeros(seg.n_docs)
                per_doc_cnt = np.zeros(seg.n_docs, np.int64)
                per_doc_min = np.full(seg.n_docs, np.inf)
                per_doc_max = np.full(seg.n_docs, -np.inf)
                sok = m[sdv.value_docs] if len(sdv.value_docs) else np.zeros(0, bool)
                np.add.at(per_doc_sum, sdv.value_docs[sok],
                          sdv.values[sok].astype(np.float64))
                np.add.at(per_doc_cnt, sdv.value_docs[sok], 1)
                np.minimum.at(per_doc_min, sdv.value_docs[sok],
                              sdv.values[sok].astype(np.float64))
                np.maximum.at(per_doc_max, sdv.value_docs[sok],
                              sdv.values[sok].astype(np.float64))
                for v, d in zip(pairs[0], pairs[1].astype(np.int64)):
                    key0 = v if dv.kind == "double" else int(v)
                    key = (sub.name, key0)
                    ps, pc, pmn, pmx = sub_parts.get(key,
                                                     (0.0, 0, np.inf, -np.inf))
                    sub_parts[key] = (ps + per_doc_sum[d],
                                      pc + int(per_doc_cnt[d]),
                                      min(pmn, per_doc_min[d]),
                                      max(pmx, per_doc_max[d]))
        return merged, sub_parts

    def _finish_sub_metric(self, sub, parts):
        s, c, mn, mx = parts
        if sub.type == "sum":
            return {"value": s}
        if sub.type == "min":
            return {"value": mn if c else None}
        if sub.type == "max":
            return {"value": mx if c else None}
        if sub.type == "avg":
            return {"value": (s / c) if c else None}
        if sub.type == "value_count":
            return {"value": c}
        if sub.type == "stats":
            return {"count": c, "min": mn if c else None,
                    "max": mx if c else None, "avg": (s / c) if c else None,
                    "sum": s}
        raise IllegalArgumentError(
            f"sub-aggregation type [{sub.type}] under terms/histogram "
            "is not supported")

    # -- histograms -------------------------------------------------------

    def _agg_histogram(self, req, seg_views):
        field, ft = self._field_type(req, "histogram")
        interval = float(req.params["interval"])
        if interval <= 0:
            raise IllegalArgumentError("[interval] must be > 0")
        offset = float(req.params.get("offset", 0))
        s, c, mn, mx = self._collect_metric_partials(field, seg_views)
        if not c:
            return {"buckets": []}
        first = np.floor((mn - offset) / interval) * interval + offset
        n = int((mx - first) // interval) + 2
        if n > MAX_BUCKETS:
            raise IllegalArgumentError(
                f"trying to create too many buckets ({n} > {MAX_BUCKETS})")
        edges = first + interval * np.arange(n, dtype=np.float64)
        return self._histogram_collect(req, field, seg_views, edges,
                                       keys=edges[:-1].tolist(),
                                       min_doc_count=int(
                                           req.params.get("min_doc_count", 0)))

    def _agg_date_histogram(self, req, seg_views):
        field, ft = self._field_type(req, "date_histogram")
        calendar = req.params.get("calendar_interval")
        fixed = req.params.get("fixed_interval") or req.params.get("interval")
        if calendar is None and fixed is None:
            raise ParsingError(
                "date_histogram requires calendar_interval or fixed_interval")
        offset = req.params.get("offset", 0)
        if isinstance(offset, str) and offset:
            offset = _parse_duration_ms(offset.lstrip("+-")) * (
                -1 if offset.startswith("-") else 1)
        s, c, mn, mx = self._collect_metric_partials(field, seg_views)
        if not c:
            return {"buckets": []}
        edges = build_date_edges(int(mn), int(mx), calendar=calendar,
                                 fixed=None if calendar else fixed,
                                 offset=int(offset))
        fmt = req.params.get("format")
        keys = edges[:-1].tolist()
        return self._histogram_collect(
            req, field, seg_views, edges, keys=keys,
            min_doc_count=int(req.params.get("min_doc_count", 0)),
            date_fmt=fmt or "")

    def _histogram_collect(self, req, field, seg_views, edges, keys,
                           min_doc_count, date_fmt=None):
        n_buckets = len(keys)
        n_pad_b = pad_pow2(n_buckets + 1)
        totals = np.zeros(n_buckets, np.int64)
        sub_parts = {sub.name: [np.zeros(n_buckets), np.zeros(n_buckets, np.int64),
                                np.full(n_buckets, np.inf),
                                np.full(n_buckets, -np.inf)]
                     for sub in req.subs}
        edges_j = jnp.asarray(edges)
        for seg, dseg, matched in seg_views:
            col = self._dev_numeric(dseg, field)
            if col is None:
                continue
            counts = np.asarray(agg_ops.bucketed_counts(
                col["values"], col["value_docs"], matched, edges_j,
                n_buckets_pad=n_pad_b))
            totals += counts[:n_buckets]
            for sub in req.subs:
                sf, _ = self._field_type(sub, sub.type)
                scol = self._dev_numeric(dseg, sf)
                if scol is None:
                    continue
                b = jnp.searchsorted(edges_j, col["values"],
                                     side="right").astype(jnp.int32) - 1
                entry_ok = (matched[col["value_docs"]] & (b >= 0)
                            & (b < len(edges) - 1))
                entry_ok &= agg_ops._first_occurrence(col["value_docs"], b)
                per_doc = agg_ops.per_doc_partials(
                    scol["values"], scol["value_docs"], matched,
                    n_pad=dseg.n_pad)
                s, c, mn, mx = agg_ops.scatter_partials_to_buckets(
                    col["value_docs"], b, entry_ok, per_doc,
                    n_buckets_pad=n_pad_b)
                acc = sub_parts[sub.name]
                acc[0] += np.asarray(s)[:n_buckets]
                acc[1] += np.asarray(c)[:n_buckets]
                acc[2] = np.minimum(acc[2], np.asarray(mn)[:n_buckets])
                acc[3] = np.maximum(acc[3], np.asarray(mx)[:n_buckets])
        buckets = []
        for i, key in enumerate(keys):
            if totals[i] < min_doc_count:
                continue
            b = {"key": int(key) if date_fmt is not None else float(key),
                 "doc_count": int(totals[i])}
            if date_fmt is not None:
                b["key_as_string"] = _fmt_date(int(key), date_fmt or None)
            for sub in req.subs:
                acc = sub_parts[sub.name]
                b[sub.name] = self._finish_sub_metric(
                    sub, (float(acc[0][i]), int(acc[1][i]),
                          float(acc[2][i]), float(acc[3][i])))
            buckets.append(b)
        return {"buckets": buckets}

    # -- mask-composition buckets ----------------------------------------

    def _narrow(self, seg_views, mask_fn):
        """New seg_views with matched &= mask_fn(seg, dseg)."""
        out = []
        for seg, dseg, matched in seg_views:
            out.append((seg, dseg, matched & mask_fn(seg, dseg)))
        return out

    def _filter_mask_fn(self, query_json):
        from opensearch_tpu.search.compiler import compile_query
        from opensearch_tpu.search.executor import build_arrays
        from opensearch_tpu.search.plan import run_full
        from opensearch_tpu.search.query_dsl import parse_query

        plan, bind = compile_query(parse_query(query_json), self.ctx,
                                   scored=False)
        needed = plan.arrays()
        neg_inf = jnp.asarray(np.float32(-np.inf))

        def mask_fn(seg, dseg):
            A = build_arrays(dseg, needed, self.ctx.mapper,
                             live=self.ctx.live_jnp(seg, dseg))
            dims, ins = plan.prepare(bind, seg, dseg, self.ctx)
            _scores, matched = run_full(plan, dims, A, ins, neg_inf)
            return matched
        return mask_fn

    def _agg_filter(self, req, seg_views):
        narrowed = self._narrow(seg_views, self._filter_mask_fn(req.params))
        out = {"doc_count": sum(int(m.sum()) for _s, _d, m in narrowed)}
        for sub in req.subs:
            out[sub.name] = self._run_one(sub, narrowed)
        return out

    def _agg_filters(self, req, seg_views):
        filters = req.params.get("filters")
        if not isinstance(filters, dict):
            raise ParsingError("[filters] aggregation requires keyed filters")
        buckets = {}
        for key, query_json in filters.items():
            narrowed = self._narrow(seg_views, self._filter_mask_fn(query_json))
            b = {"doc_count": sum(int(m.sum()) for _s, _d, m in narrowed)}
            for sub in req.subs:
                b[sub.name] = self._run_one(sub, narrowed)
            buckets[key] = b
        return {"buckets": buckets}

    def _agg_global(self, req, seg_views):
        widened = [(seg, dseg, self.ctx.live_jnp(seg, dseg))
                   for seg, dseg, _m in seg_views]
        out = {"doc_count": sum(int(m.sum()) for _s, _d, m in widened)}
        for sub in req.subs:
            out[sub.name] = self._run_one(sub, widened)
        return out

    def _agg_missing(self, req, seg_views):
        field, ft = self._field_type(req, "missing")
        from opensearch_tpu.search.query_dsl import ExistsQuery
        from opensearch_tpu.search.compiler import compile_query
        from opensearch_tpu.search.executor import build_arrays
        from opensearch_tpu.search.plan import run_full

        plan, bind = compile_query(ExistsQuery(field=field), self.ctx,
                                   scored=False)
        needed = plan.arrays()
        neg_inf = jnp.asarray(np.float32(-np.inf))

        def mask_fn(seg, dseg):
            A = build_arrays(dseg, needed, self.ctx.mapper,
                             live=self.ctx.live_jnp(seg, dseg))
            dims, ins = plan.prepare(bind, seg, dseg, self.ctx)
            _s, exists = run_full(plan, dims, A, ins, neg_inf)
            return ~exists & self.ctx.live_jnp(seg, dseg)
        narrowed = self._narrow(seg_views, mask_fn)
        out = {"doc_count": sum(int(m.sum()) for _s, _d, m in narrowed)}
        for sub in req.subs:
            out[sub.name] = self._run_one(sub, narrowed)
        return out

    def _agg_range(self, req, seg_views, is_date=False):
        field, ft = self._field_type(req, "range")
        ranges = req.params.get("ranges")
        if not ranges:
            raise ParsingError("[range] aggregation requires [ranges]")
        buckets = []
        for r in ranges:
            frm = r.get("from")
            to = r.get("to")
            if is_date:
                frm_v = parse_date_millis(frm) if frm is not None else None
                to_v = parse_date_millis(to) if to is not None else None
            else:
                frm_v = float(frm) if frm is not None else None
                to_v = float(to) if to is not None else None

            def mask_fn(seg, dseg, frm_v=frm_v, to_v=to_v):
                col = self._dev_numeric(dseg, field)
                if col is None:
                    return jnp.zeros(dseg.n_pad, bool)
                from opensearch_tpu.ops.filters import range_mask
                lo = -np.inf if frm_v is None else frm_v
                hi = np.inf if to_v is None else to_v
                vals = col["values"].astype(jnp.float64)
                return range_mask(vals, col["value_docs"], lo, hi,
                                  include_lo=True, include_hi=False,
                                  n_pad=dseg.n_pad)
            narrowed = self._narrow(seg_views, mask_fn)
            key = r.get("key")
            if key is None:
                key = (f"{'*' if frm is None else frm}-"
                       f"{'*' if to is None else to}")
            b = {"key": key, "doc_count":
                 sum(int(m.sum()) for _s, _d, m in narrowed)}
            if frm is not None:
                b["from"] = frm_v
            if to is not None:
                b["to"] = to_v
            for sub in req.subs:
                b[sub.name] = self._run_one(sub, narrowed)
            buckets.append(b)
        return {"buckets": buckets}

    def _agg_date_range(self, req, seg_views):
        return self._agg_range(req, seg_views, is_date=True)
