"""Pipeline aggregations: coordinator-side transforms over reduced aggs.

The reference runs these after the final reduce (ref
search/aggregations/pipeline/PipelineAggregator.java — sibling aggs via
SiblingPipelineAggregator.doReduce, parent aggs via each
*PipelineAggregator.reduce over the parent's bucket list).  Nothing
touches the device: inputs are the already-reduced response buckets, so
this is pure host reduce-tree work applied by ``reduce_aggs`` as a
post-pass — identical for the 1-shard and N-shard partial-merge paths.

All 15 reference types (SURVEY Appendix A listing of
``search/aggregations/pipeline/``):

  sibling:  avg_bucket, max_bucket, min_bucket, sum_bucket, stats_bucket,
            extended_stats_bucket, percentiles_bucket
  parent:   cumulative_sum, derivative, serial_diff, moving_fn,
            moving_avg (legacy model-based alias), bucket_script,
            bucket_selector, bucket_sort

Window semantics follow MovFnPipelineAggregator.java:136 — the window is
``[i - window + shift, i + shift)``, i.e. shift=0 EXCLUDES the current
bucket; MovAvgPipelineAggregator.java:122 computes the model before
offering the current value, so moving_avg shares the same exclusive
window.
"""

from __future__ import annotations

import ast

import numpy as np

from opensearch_tpu.common.errors import IllegalArgumentError, ParsingError

PARENT_TYPES = {"cumulative_sum", "derivative", "serial_diff", "moving_fn",
                "moving_avg", "bucket_script", "bucket_selector",
                "bucket_sort"}
SIBLING_TYPES = {"avg_bucket", "max_bucket", "min_bucket", "sum_bucket",
                 "stats_bucket", "extended_stats_bucket",
                 "percentiles_bucket"}
PIPELINE_TYPES = PARENT_TYPES | SIBLING_TYPES

_GAP = ("skip", "insert_zeros", "keep_values")


# -- buckets_path resolution ----------------------------------------------

def _gap_policy(params) -> str:
    gp = params.get("gap_policy", "skip")
    if gp not in _GAP:
        raise ParsingError(f"No gap policy found for value [{gp}]")
    return gp


def _metric_value(node, stat: str | None):
    """Extract a numeric from one reduced agg output."""
    if node is None:
        return None
    if stat is None:
        if "value" in node:
            return node["value"]
        raise IllegalArgumentError(
            "buckets_path must reference either a number value or a "
            "single value numeric metric aggregation")
    if stat in node:
        return node[stat]
    vals = node.get("values")
    if isinstance(vals, dict):
        for key in (stat, f"{float(stat)}" if _is_num(stat) else stat):
            if key in vals:
                return vals[key]
    raise IllegalArgumentError(f"path not supported for [{stat}]")


def _is_num(s) -> bool:
    try:
        float(s)
        return True
    except (TypeError, ValueError):
        return False


def bucket_value(bucket: dict, path: str, gap_policy: str = "skip"):
    """Value of ``path`` relative to one bucket ("_count", "metric",
    "single_bucket>metric", "stats_metric.avg"...).  Returns None for a
    gap under skip, 0.0 under insert_zeros."""
    parts = path.split(">")
    node = bucket
    for part in parts[:-1]:
        node = node.get(part.strip())
        if node is None:
            return _gap(gap_policy)
    last = parts[-1].strip()
    if last == "_count":
        return float(node["doc_count"])
    name, dot, stat = last.partition(".")
    v = _metric_value(node.get(name), stat if dot else None)
    if v is None or (isinstance(v, float) and np.isnan(v)):
        return _gap(gap_policy)
    return float(v)


def _gap(gap_policy: str):
    return 0.0 if gap_policy == "insert_zeros" else None


def _buckets_list(node):
    """Bucket list of a reduced multi-bucket agg (list, or keyed dict as
    in filters{keyed})."""
    b = node.get("buckets")
    if isinstance(b, dict):
        return list(b.values())
    return b


def sibling_values(level: dict, path: str, gap_policy: str):
    """Resolve a sibling buckets_path like "histo>metric[.stat]" against
    the reduced aggs at one level: walks single-bucket aggs, then maps
    over the multi-bucket agg's buckets.  Returns (values, keys)."""
    parts = [p.strip() for p in path.split(">")]
    node = level
    for i, part in enumerate(parts):
        nxt = node.get(part) if isinstance(node, dict) else None
        if nxt is None and "." in part and isinstance(node, dict):
            # "agg.metric" dot form: split at the first dot that names
            # an agg at this level (BucketsPath's AGG_PATH separators)
            name, _, rest = part.partition(".")
            if name in node:
                nxt = node[name]
                parts = parts[:i] + [name, rest] + parts[i + 1:]
                part = name
        if nxt is None:
            raise IllegalArgumentError(
                f"No aggregation found for path [{path}]")
        if "buckets" in nxt:
            rest = ">".join(parts[i + 1:])
            if not rest:
                raise IllegalArgumentError(
                    f"No aggregation [metric] found for path [{path}]")
            vals, keys = [], []
            for b in _buckets_list(nxt):
                if gap_policy == "skip" and b.get("doc_count") == 0:
                    # empty buckets are gaps to sibling metrics
                    # (BucketMetricsPipelineAggregator.collectBucketValue)
                    vals.append(None)
                else:
                    vals.append(bucket_value(b, rest, gap_policy))
                keys.append(b.get("key"))
            return vals, keys
        node = nxt                      # single-bucket: descend
    raise IllegalArgumentError(
        f"buckets_path [{path}] must reference a multi-bucket aggregation")


# -- host scalar script evaluation (bucket_script / bucket_selector) ------

def _eval_bucket_script(script, variables: dict):
    """Painless-subset scalar evaluation over resolved buckets_path
    variables (exposed as ``params.*`` plus bare names, matching
    BucketScriptPipelineAggregator.java:113)."""
    from opensearch_tpu.search.scripting import (ScriptException,
                                                 _Evaluator,
                                                 _FieldCollector,
                                                 _painless_to_python)

    if isinstance(script, dict):
        src = script.get("source") or script.get("inline")
        params = dict(script.get("params") or {})
    else:
        src, params = str(script), {}
    if src is None:
        raise ParsingError("[script] requires a [source]")
    params.update(variables)
    try:
        tree = ast.parse(_painless_to_python(src), mode="eval")
    except SyntaxError as e:
        raise ScriptException(f"compile error in [{src}]: {e}") from None

    # the scoring whitelist, extended: bare buckets_path variable names
    # are legal in bucket-script painless (exposed alongside params.*,
    # BucketScriptPipelineAggregator.java:113)
    class _Whitelist(_FieldCollector):
        def visit_Name(self, node):
            if node.id in params:
                return
            return super().visit_Name(node)

    wl = _Whitelist()
    wl.visit(tree)
    if wl.numeric or wl.vectors:
        raise ScriptException(
            "doc[...] is not available in pipeline aggregations")

    class _Eval(_Evaluator):
        def visit_Name(self, node):
            if node.id in params:
                return self._param(node.id)
            return super().visit_Name(node)

    return _Eval(params, {}, {}, 0.0).visit(tree)


# -- moving_fn scripts ----------------------------------------------------

def _mf_stddev(values, avg):
    v = values[~np.isnan(values)]
    if len(v) == 0:
        return float("nan")
    return float(np.sqrt(np.mean((v - avg) ** 2)))


def _mf_linear(values):
    v = values[~np.isnan(values)]
    if len(v) == 0:
        return float("nan")
    w = np.arange(1, len(v) + 1, dtype=np.float64)
    return float((v * w).sum() / w.sum())


def _mf_ewma(values, alpha):
    v = values[~np.isnan(values)]
    if len(v) == 0:
        return float("nan")
    avg = v[0]
    for x in v[1:]:
        avg = alpha * x + (1 - alpha) * avg
    return float(avg)


def _mf_holt(values, alpha, beta):
    v = values[~np.isnan(values)]
    if len(v) == 0:
        return float("nan")
    if len(v) == 1:
        return float(v[0])
    s = v[0]
    b = v[1] - v[0]
    for i in range(1, len(v)):
        last_s = s
        s = alpha * v[i] + (1 - alpha) * (s + b)
        b = beta * (s - last_s) + (1 - beta) * b
    return float(s + b)


def _nan_reduce(fn):
    def run(values):
        v = values[~np.isnan(values)]
        return float(fn(v)) if len(v) else float("nan")
    return run


_MOVING_FNS = {
    "max": _nan_reduce(np.max),
    "min": _nan_reduce(np.min),
    "sum": lambda v: float(np.nansum(v)) if len(v[~np.isnan(v)]) else 0.0,
    "unweightedAvg": _nan_reduce(np.mean),
    "stdDev": _mf_stddev,
    "linearWeightedAvg": _mf_linear,
    "ewma": _mf_ewma,
    "holt": _mf_holt,
}


def _eval_moving_fn(script, window_values: np.ndarray):
    """Evaluate a moving_fn script: ``MovingFunctions.<fn>(values, ...)``
    (MovingFunctions.java whitelist) over one window."""
    if isinstance(script, dict):
        src = script.get("source") or script.get("inline") or ""
    else:
        src = str(script)
    try:
        tree = ast.parse(src.strip(), mode="eval")
    except SyntaxError:
        raise ParsingError(f"invalid moving_fn script [{src}]") from None

    def ev(node):
        if isinstance(node, ast.Expression):
            return ev(node.body)
        if isinstance(node, ast.Constant) and isinstance(node.value,
                                                         (int, float)):
            return float(node.value)
        if isinstance(node, ast.Name) and node.id == "values":
            return window_values
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "MovingFunctions"):
            fn = _MOVING_FNS.get(node.func.attr)
            if fn is None:
                raise ParsingError(
                    f"unknown MovingFunctions.{node.func.attr}")
            return fn(*[ev(a) for a in node.args])
        if isinstance(node, ast.BinOp):
            import operator as op

            ops = {ast.Add: op.add, ast.Sub: op.sub, ast.Mult: op.mul,
                   ast.Div: op.truediv}
            fn = ops.get(type(node.op))
            if fn is not None:
                return fn(ev(node.left), ev(node.right))
        raise ParsingError("unsupported moving_fn script construct")

    return ev(tree)


# -- parent pipelines -----------------------------------------------------

def _apply_parent(req, buckets: list):
    """Apply one parent pipeline agg to the parent's bucket list,
    returning the (possibly filtered/reordered) list."""
    params = req.params
    typ = req.type
    gp = _gap_policy(params)
    if typ in ("cumulative_sum", "derivative", "serial_diff",
               "moving_fn", "moving_avg"):
        path = params.get("buckets_path")
        if path is None:
            raise ParsingError(f"[{typ}] requires [buckets_path]")
        if isinstance(path, list):
            path = path[0]
        vals = [bucket_value(b, path, gp) for b in buckets]
        if typ == "cumulative_sum":
            # gaps contribute nothing but still get the running total
            # (CumulativeSumPipelineAggregator.java)
            total = 0.0
            for b, v in zip(buckets, vals):
                total += v if v is not None else 0.0
                b[req.name] = {"value": total}
        elif typ == "derivative":
            unit = params.get("unit")
            unit_ms = None
            if unit is not None:
                from opensearch_tpu.search.aggs import _parse_duration_ms
                unit_ms = _parse_duration_ms(unit) if not str(
                    unit).isdigit() else int(unit)
            prev = prev_key = None
            for b, v in zip(buckets, vals):
                if prev is not None and v is not None:
                    diff = v - prev
                    out = {"value": diff}
                    if unit_ms and b.get("key") is not None \
                            and prev_key is not None:
                        span = (float(b["key"]) - float(prev_key)) / unit_ms
                        out["normalized_value"] = diff / span if span else None
                    b[req.name] = out
                if v is not None:
                    # a gap never clears the carried value (the reference
                    # leaves lastBucketValue untouched on NaN under every
                    # gap policy — DerivativePipelineAggregator.java)
                    prev, prev_key = v, b.get("key")
        elif typ == "serial_diff":
            lag = int(params.get("lag", 1))
            if lag < 1:
                raise IllegalArgumentError("[lag] must be a positive integer")
            hist = []
            for b, v in zip(buckets, vals):
                if len(hist) >= lag and v is not None \
                        and hist[-lag] is not None:
                    b[req.name] = {"value": v - hist[-lag]}
                hist.append(v)
        else:                                   # moving_fn / moving_avg
            window = int(params.get("window", 5))
            if window <= 0:
                raise IllegalArgumentError("[window] must be a positive "
                                           "integer")
            shift = int(params.get("shift", 0))
            arr = np.asarray([np.nan if v is None else v for v in vals],
                             np.float64)
            if typ == "moving_avg":
                script = _movavg_model_script(params)
            else:
                script = params.get("script")
                if script is None:
                    raise ParsingError("[moving_fn] requires [script]")
            n = len(arr)
            for i, b in enumerate(buckets):
                lo = max(0, min(i - window + shift, n))
                hi = max(0, min(i + shift, n))
                res = _eval_moving_fn(script, arr[lo:hi])
                if res is not None and not (isinstance(res, float)
                                            and np.isnan(res)):
                    b[req.name] = {"value": float(res)}
        return buckets
    if typ == "bucket_script":
        paths = params.get("buckets_path")
        if not isinstance(paths, dict):
            raise ParsingError("[bucket_script] requires a [buckets_path] "
                               "map")
        script = params.get("script")
        for b in buckets:
            vars_ = {}
            gap = False
            for var, p in paths.items():
                v = bucket_value(b, p, gp)
                if v is None:
                    gap = True
                    break
                vars_[var] = v
            if gap:
                continue
            val = _eval_bucket_script(script, vars_)
            b[req.name] = {"value": float(val)}
        return buckets
    if typ == "bucket_selector":
        paths = params.get("buckets_path")
        if not isinstance(paths, dict):
            raise ParsingError("[bucket_selector] requires a [buckets_path] "
                               "map")
        script = params.get("script")
        kept = []
        for b in buckets:
            vars_ = {}
            gap = False
            for var, p in paths.items():
                v = bucket_value(b, p, gp)
                if v is None:
                    gap = True
                    break
                vars_[var] = v
            if gap or bool(_eval_bucket_script(script, vars_)):
                kept.append(b)
        return kept
    if typ == "bucket_sort":
        sort = params.get("sort") or []
        from_ = int(params.get("from", 0))
        size = params.get("size")
        if sort:
            import functools

            keys = []
            for spec in sort:
                if isinstance(spec, str):
                    spec = {spec: {"order": "asc"}}
                ((path, opts),) = spec.items()
                order = (opts or {}).get("order", "desc") \
                    if isinstance(opts, dict) else "desc"
                keys.append((path, order == "desc"))

            def val_of(b, path):
                return b.get("key") if path == "_key" \
                    else bucket_value(b, path, gp)

            def cmp(a, b):
                # per-key comparison: None always sorts last; desc flips
                # the comparison, never negates (string keys sort too)
                for path, desc in keys:
                    va, vb = val_of(a, path), val_of(b, path)
                    if va == vb:
                        continue
                    if va is None:
                        return 1
                    if vb is None:
                        return -1
                    lt = va < vb
                    if desc:
                        lt = not lt
                    return -1 if lt else 1
                return 0

            buckets = sorted(buckets, key=functools.cmp_to_key(cmp))
        end = None if size is None else from_ + int(size)
        return buckets[from_:end]
    raise ParsingError(f"unknown pipeline aggregation [{typ}]")


def _movavg_model_script(params) -> str:
    """Legacy moving_avg model -> the equivalent MovingFunctions call
    (the same mapping the reference documents for migrating off
    MovAvgPipelineAggregator)."""
    model = params.get("model", "simple")
    s = params.get("settings") or {}
    if model == "simple":
        return "MovingFunctions.unweightedAvg(values)"
    if model == "linear":
        return "MovingFunctions.linearWeightedAvg(values)"
    if model == "ewma":
        return f"MovingFunctions.ewma(values, {float(s.get('alpha', 0.3))})"
    if model == "holt":
        return (f"MovingFunctions.holt(values, "
                f"{float(s.get('alpha', 0.3))}, {float(s.get('beta', 0.1))})")
    raise ParsingError(f"moving_avg model [{model}] is not supported "
                       "(use moving_fn for holt_winters)")


# -- sibling pipelines ----------------------------------------------------

def _sibling_result(req, level: dict):
    params = req.params
    gp = _gap_policy(params)
    path = params.get("buckets_path")
    if path is None:
        raise ParsingError(f"[{req.type}] requires [buckets_path]")
    if isinstance(path, list):
        path = path[0]
    vals, keys = sibling_values(level, path, gp)
    pairs = [(v, k) for v, k in zip(vals, keys) if v is not None]
    clean = np.asarray([v for v, _ in pairs], np.float64)
    typ = req.type
    if typ == "avg_bucket":
        return {"value": float(clean.mean()) if len(clean) else None}
    if typ == "sum_bucket":
        return {"value": float(clean.sum()) if len(clean) else 0.0}
    if typ in ("max_bucket", "min_bucket"):
        if not len(clean):
            return {"value": None, "keys": []}
        best = float(clean.max() if typ == "max_bucket" else clean.min())
        ks = [str(k) for v, k in pairs if v == best]
        return {"value": best, "keys": ks}
    if typ == "stats_bucket":
        if not len(clean):
            return {"count": 0, "min": None, "max": None, "avg": None,
                    "sum": 0.0}
        return {"count": int(len(clean)), "min": float(clean.min()),
                "max": float(clean.max()), "avg": float(clean.mean()),
                "sum": float(clean.sum())}
    if typ == "extended_stats_bucket":
        sigma = float(params.get("sigma", 2.0))
        n = len(clean)
        if not n:
            return {"count": 0, "min": None, "max": None, "avg": None,
                    "sum": 0.0, "sum_of_squares": None, "variance": None,
                    "std_deviation": None,
                    "std_deviation_bounds": {"upper": None, "lower": None}}
        sq = float((clean ** 2).sum())
        avg = float(clean.mean())
        var = sq / n - avg * avg
        std = float(np.sqrt(max(var, 0.0)))
        return {"count": n, "min": float(clean.min()),
                "max": float(clean.max()), "avg": avg,
                "sum": float(clean.sum()), "sum_of_squares": sq,
                "variance": var, "variance_population": var,
                "variance_sampling": (sq - n * avg * avg) / (n - 1)
                if n > 1 else None,
                "std_deviation": std, "std_deviation_population": std,
                "std_deviation_sampling": float(np.sqrt(max(
                    (sq - n * avg * avg) / (n - 1), 0.0)))
                if n > 1 else None,
                "std_deviation_bounds": {"upper": avg + sigma * std,
                                         "lower": avg - sigma * std}}
    if typ == "percentiles_bucket":
        percents = params.get("percents",
                              [1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0])
        if not len(clean):
            return {"values": {f"{float(p)}": None for p in percents}}
        # the reference uses the nearest-rank method over the sorted
        # bucket values (PercentilesBucketPipelineAggregator.java:126)
        s = np.sort(clean)
        out = {}
        for p in percents:
            i = int(round(float(p) / 100.0 * len(s))) - 1
            out[f"{float(p)}"] = float(s[max(0, min(i, len(s) - 1))])
        return {"values": out}
    raise ParsingError(f"unknown pipeline aggregation [{typ}]")


# -- tree application -----------------------------------------------------

def apply_pipelines(reqs: list, out: dict):
    """Post-reduce pass over one reduced aggs level: recurse into bucket
    trees, run parent pipelines inside their parent's buckets, then
    sibling pipelines at this level — all in declaration order so chains
    (derivative of cumulative_sum, max_bucket of derivative) work."""
    for r in reqs:
        if r.type in PARENT_TYPES:
            # parent pipelines only make sense inside a multi-bucket agg
            # (the reference 400s at validate(); silently dropping the
            # name would hide the mistake from the client)
            raise IllegalArgumentError(
                f"[{r.type}] aggregation [{r.name}] must be declared "
                "inside a multi-bucket aggregation")
    for r in reqs:
        if r.type in PIPELINE_TYPES:
            continue
        node = out.get(r.name)
        if node is not None:
            _apply_in_agg(r, node)
    for r in reqs:
        if r.type in SIBLING_TYPES:
            out[r.name] = _sibling_result(r, out)
    return out


def _apply_in_agg(req, node: dict):
    """Recurse + apply the pipeline subs of one reduced bucket agg."""
    buckets = node.get("buckets")
    if buckets is None:
        # single-bucket agg (filter/global/missing): its subs live as
        # named keys on the node itself — treat the node as one level
        if "doc_count" in node and req.subs:
            apply_pipelines(req.subs, node)
        return
    keyed = isinstance(buckets, dict)
    blist = list(buckets.values()) if keyed else buckets
    # deeper levels first
    for b in blist:
        for sub in req.subs:
            if sub.type in PIPELINE_TYPES:
                continue
            sub_node = b.get(sub.name)
            if sub_node is not None:
                _apply_in_agg(sub, sub_node)
    # sibling pipes nested one level down operate within each bucket
    for b in blist:
        for sub in req.subs:
            if sub.type in SIBLING_TYPES:
                b[sub.name] = _sibling_result(sub, b)
    # parent pipes transform the bucket list in declaration order
    for sub in req.subs:
        if sub.type in PARENT_TYPES:
            blist = _apply_parent(sub, blist)
    if keyed:
        # rebuild the keyed dict in the (possibly sorted/filtered)
        # bucket order — JSON key order carries bucket_sort's result
        by_id = {id(b): k for k, b in buckets.items()}
        node["buckets"] = {by_id[id(b)]: b for b in blist
                           if id(b) in by_id}
    else:
        node["buckets"] = blist
