"""Query DSL: JSON -> typed query tree.

Analog of the reference's ``index/query/*QueryBuilder`` classes (47 builders,
server/src/main/java/org/opensearch/index/query/; parsed via
``AbstractQueryBuilder.parseInnerQueryBuilder``).  Parsing is independent of
any shard: the tree is compiled against a shard's segments by
``opensearch_tpu.search.plan`` (the ``toQuery(QueryShardContext)`` analog,
ref index/query/QueryShardContext.java:95).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Optional

from opensearch_tpu.common.errors import ParsingError


@dataclass
class Query:
    boost: float = 1.0


@dataclass
class MatchAllQuery(Query):
    pass


@dataclass
class MatchNoneQuery(Query):
    pass


@dataclass
class TermQuery(Query):
    field: str = ""
    value: Any = None


@dataclass
class TermsQuery(Query):
    field: str = ""
    values: list = dc_field(default_factory=list)


@dataclass
class MatchQuery(Query):
    field: str = ""
    query: Any = None
    operator: str = "or"            # or | and
    minimum_should_match: Optional[str] = None
    fuzziness: Optional[str] = None


@dataclass
class MatchPhraseQuery(Query):
    field: str = ""
    query: Any = None
    slop: int = 0


@dataclass
class MultiMatchQuery(Query):
    fields: list = dc_field(default_factory=list)   # [(field, boost)]
    query: Any = None
    type: str = "best_fields"        # best_fields | most_fields | phrase
    operator: str = "or"
    tie_breaker: float = 0.0
    minimum_should_match: Optional[str] = None


@dataclass
class BoolQuery(Query):
    must: list = dc_field(default_factory=list)
    should: list = dc_field(default_factory=list)
    must_not: list = dc_field(default_factory=list)
    filter: list = dc_field(default_factory=list)
    minimum_should_match: Optional[str] = None


@dataclass
class RangeQuery(Query):
    field: str = ""
    gte: Any = None
    gt: Any = None
    lte: Any = None
    lt: Any = None
    fmt: Optional[str] = None
    time_zone: Optional[str] = None


@dataclass
class ExistsQuery(Query):
    field: str = ""


@dataclass
class IdsQuery(Query):
    values: list = dc_field(default_factory=list)


@dataclass
class PrefixQuery(Query):
    field: str = ""
    value: str = ""


@dataclass
class WildcardQuery(Query):
    field: str = ""
    value: str = ""


@dataclass
class RegexpQuery(Query):
    field: str = ""
    value: str = ""


@dataclass
class FuzzyQuery(Query):
    field: str = ""
    value: str = ""
    fuzziness: str = "AUTO"
    prefix_length: int = 0


@dataclass
class ConstantScoreQuery(Query):
    query: Optional[Query] = None


@dataclass
class DisMaxQuery(Query):
    queries: list = dc_field(default_factory=list)
    tie_breaker: float = 0.0


@dataclass
class KnnQuery(Query):
    field: str = ""
    vector: list = dc_field(default_factory=list)
    k: int = 10
    filter: Optional[Query] = None
    # per-request ANN overrides, e.g. {"nprobe": 16} (method_parameters
    # in the opensearch-knn request shape)
    method_parameters: Optional[dict] = None


@dataclass
class HybridQuery(Query):
    """Independent sub-queries whose scores a search pipeline's
    normalization processor combines (the neural-search plugin's hybrid
    query; executes per sub-query, never as one plan)."""

    queries: list = dc_field(default_factory=list)


@dataclass
class ScriptScoreQuery(Query):
    query: Optional[Query] = None
    script: dict = dc_field(default_factory=dict)
    min_score: Optional[float] = None


@dataclass
class SimpleQueryStringQuery(Query):
    query: str = ""
    fields: list = dc_field(default_factory=list)
    default_operator: str = "or"


def _field_kv(body: dict, qname: str) -> tuple[str, Any]:
    if len(body) != 1:
        raise ParsingError(f"[{qname}] query must reference exactly one field, got {sorted(body)}")
    return next(iter(body.items()))


def _as_list(v) -> list:
    return v if isinstance(v, list) else [v]


def _boost(body) -> float:
    return float(body.get("boost", 1.0)) if isinstance(body, dict) else 1.0


def _parse_fields_with_boosts(fields: list) -> list[tuple[str, float]]:
    out = []
    for f in fields:
        if "^" in f:
            name, _, b = f.partition("^")
            out.append((name, float(b)))
        else:
            out.append((f, 1.0))
    return out


def parse_query(obj: Optional[dict]) -> Query:
    """Parse one query object ``{"<type>": {...}}`` into a Query tree."""
    if obj is None:
        return MatchAllQuery()
    if not isinstance(obj, dict):
        raise ParsingError(f"malformed query, expected an object but got [{obj}]")
    if not obj:
        return MatchAllQuery()
    if len(obj) != 1:
        raise ParsingError(
            f"malformed query, expected one top-level key but got {sorted(obj)}")
    qname, body = next(iter(obj.items()))
    parser = _PARSERS.get(qname)
    if parser is None:
        raise ParsingError(f"unknown query [{qname}]")
    return parser(body)


def _parse_match_all(body):
    return MatchAllQuery(boost=_boost(body))


def _parse_match_none(body):
    return MatchNoneQuery()


def _parse_term(body):
    field, v = _field_kv(body, "term")
    if isinstance(v, dict):
        return TermQuery(field=field, value=v.get("value"), boost=_boost(v))
    return TermQuery(field=field, value=v)


def _parse_terms(body):
    rest = {k: v for k, v in body.items() if k != "boost"}
    field, vals = _field_kv(rest, "terms")
    if not isinstance(vals, list):
        raise ParsingError("[terms] query requires an array of values")
    return TermsQuery(field=field, values=vals, boost=_boost(body))


def _parse_match(body):
    field, v = _field_kv(body, "match")
    if isinstance(v, dict):
        return MatchQuery(
            field=field, query=v.get("query"),
            operator=str(v.get("operator", "or")).lower(),
            minimum_should_match=(
                None if v.get("minimum_should_match") is None
                else str(v.get("minimum_should_match"))),
            fuzziness=v.get("fuzziness"),
            boost=_boost(v))
    return MatchQuery(field=field, query=v)


def _parse_match_phrase(body):
    field, v = _field_kv(body, "match_phrase")
    if isinstance(v, dict):
        return MatchPhraseQuery(field=field, query=v.get("query"),
                                slop=int(v.get("slop", 0)), boost=_boost(v))
    return MatchPhraseQuery(field=field, query=v)


def _parse_multi_match(body):
    typ = str(body.get("type", "best_fields"))
    tie = body.get("tie_breaker")
    return MultiMatchQuery(
        fields=_parse_fields_with_boosts(body.get("fields", [])),
        query=body.get("query"),
        type=typ,
        operator=str(body.get("operator", "or")).lower(),
        tie_breaker=float(tie) if tie is not None else (1.0 if typ == "most_fields" else 0.0),
        minimum_should_match=(
            None if body.get("minimum_should_match") is None
            else str(body.get("minimum_should_match"))),
        boost=_boost(body))


def _parse_bool(body):
    msm = body.get("minimum_should_match")
    return BoolQuery(
        must=[parse_query(q) for q in _as_list(body.get("must", []))],
        should=[parse_query(q) for q in _as_list(body.get("should", []))],
        must_not=[parse_query(q) for q in _as_list(body.get("must_not", []))],
        filter=[parse_query(q) for q in _as_list(body.get("filter", []))],
        minimum_should_match=None if msm is None else str(msm),
        boost=_boost(body))


def _parse_range(body):
    field, v = _field_kv(body, "range")
    if not isinstance(v, dict):
        raise ParsingError("[range] query requires bounds object")
    known = {"gte", "gt", "lte", "lt", "from", "to", "include_lower",
             "include_upper", "boost", "format", "time_zone", "relation"}
    unknown = set(v) - known
    if unknown:
        raise ParsingError(f"[range] query does not support {sorted(unknown)}")
    gte, gt, lte, lt = v.get("gte"), v.get("gt"), v.get("lte"), v.get("lt")
    # legacy from/to form
    if "from" in v:
        if v.get("include_lower", True):
            gte = v["from"]
        else:
            gt = v["from"]
    if "to" in v:
        if v.get("include_upper", True):
            lte = v["to"]
        else:
            lt = v["to"]
    return RangeQuery(field=field, gte=gte, gt=gt, lte=lte, lt=lt,
                      fmt=v.get("format"), time_zone=v.get("time_zone"),
                      boost=_boost(v))


def _parse_exists(body):
    return ExistsQuery(field=body["field"], boost=_boost(body))


def _parse_ids(body):
    return IdsQuery(values=list(body.get("values", [])), boost=_boost(body))


def _term_like(cls, qname):
    def parse(body):
        field, v = _field_kv(body, qname)
        if isinstance(v, dict):
            return cls(field=field, value=v.get("value"), boost=_boost(v))
        return cls(field=field, value=v)
    return parse


def _parse_fuzzy(body):
    field, v = _field_kv(body, "fuzzy")
    if isinstance(v, dict):
        return FuzzyQuery(field=field, value=str(v.get("value")),
                          fuzziness=str(v.get("fuzziness", "AUTO")),
                          prefix_length=int(v.get("prefix_length", 0)),
                          boost=_boost(v))
    return FuzzyQuery(field=field, value=str(v))


def _parse_constant_score(body):
    return ConstantScoreQuery(query=parse_query(body.get("filter")), boost=_boost(body))


def _parse_dis_max(body):
    return DisMaxQuery(queries=[parse_query(q) for q in body.get("queries", [])],
                       tie_breaker=float(body.get("tie_breaker", 0.0)),
                       boost=_boost(body))


def _parse_knn(body):
    # Accept both the opensearch-knn plugin shape {field: {vector, k}} and a
    # flat {field, query_vector, k} shape.
    if "field" in body and ("query_vector" in body or "vector" in body):
        return KnnQuery(field=body["field"],
                        vector=list(body.get("query_vector") or body.get("vector")),
                        k=int(body.get("k", 10)),
                        filter=parse_query(body["filter"]) if body.get("filter") else None,
                        method_parameters=body.get("method_parameters"),
                        boost=_boost(body))
    field, v = _field_kv({k: v for k, v in body.items() if k != "boost"}, "knn")
    return KnnQuery(field=field, vector=list(v["vector"]), k=int(v.get("k", 10)),
                    filter=parse_query(v["filter"]) if v.get("filter") else None,
                    method_parameters=v.get("method_parameters"),
                    boost=_boost(v))


def _parse_hybrid(body):
    qs = body.get("queries")
    if not isinstance(qs, list) or not qs:
        raise ParsingError("[hybrid] query requires a [queries] array")
    if len(qs) > 5:
        raise ParsingError("[hybrid] supports at most 5 sub-queries")
    return HybridQuery(queries=[parse_query(q) for q in qs],
                       boost=_boost(body))


def _parse_script_score(body):
    ms = body.get("min_score")
    return ScriptScoreQuery(query=parse_query(body.get("query")),
                            script=body.get("script", {}),
                            min_score=float(ms) if ms is not None else None,
                            boost=_boost(body))


def _parse_simple_query_string(body):
    return SimpleQueryStringQuery(
        query=str(body.get("query", "")),
        fields=_parse_fields_with_boosts(body.get("fields", ["*"])),
        default_operator=str(body.get("default_operator", "or")).lower(),
        boost=_boost(body))


_PARSERS = {
    "match_all": _parse_match_all,
    "match_none": _parse_match_none,
    "term": _parse_term,
    "terms": _parse_terms,
    "match": _parse_match,
    "match_phrase": _parse_match_phrase,
    "multi_match": _parse_multi_match,
    "bool": _parse_bool,
    "range": _parse_range,
    "exists": _parse_exists,
    "ids": _parse_ids,
    "prefix": _term_like(PrefixQuery, "prefix"),
    "wildcard": _term_like(WildcardQuery, "wildcard"),
    "regexp": _term_like(RegexpQuery, "regexp"),
    "fuzzy": _parse_fuzzy,
    "constant_score": _parse_constant_score,
    "dis_max": _parse_dis_max,
    "knn": _parse_knn,
    "script_score": _parse_script_score,
    "hybrid": _parse_hybrid,
    "simple_query_string": _parse_simple_query_string,
}
