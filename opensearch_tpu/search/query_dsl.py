"""Query DSL: JSON -> typed query tree.

Analog of the reference's ``index/query/*QueryBuilder`` classes (47 builders,
server/src/main/java/org/opensearch/index/query/; parsed via
``AbstractQueryBuilder.parseInnerQueryBuilder``).  Parsing is independent of
any shard: the tree is compiled against a shard's segments by
``opensearch_tpu.search.plan`` (the ``toQuery(QueryShardContext)`` analog,
ref index/query/QueryShardContext.java:95).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as dc_field
from typing import Any, Optional

from opensearch_tpu.common.errors import ParsingError


@dataclass
class Query:
    boost: float = 1.0


@dataclass
class MatchAllQuery(Query):
    pass


@dataclass
class MatchNoneQuery(Query):
    pass


@dataclass
class TermQuery(Query):
    field: str = ""
    value: Any = None


@dataclass
class TermsQuery(Query):
    field: str = ""
    values: list = dc_field(default_factory=list)


@dataclass
class MatchQuery(Query):
    field: str = ""
    query: Any = None
    operator: str = "or"            # or | and
    minimum_should_match: Optional[str] = None
    fuzziness: Optional[str] = None
    lenient: bool = False           # format mismatch -> no match, not 400
    analyzer: Optional[str] = None


@dataclass
class MatchPhraseQuery(Query):
    field: str = ""
    query: Any = None
    slop: int = 0


@dataclass
class MatchPhrasePrefixQuery(Query):
    field: str = ""
    query: Any = None
    slop: int = 0
    max_expansions: int = 50


@dataclass
class MatchBoolPrefixQuery(Query):
    field: str = ""
    query: Any = None
    operator: str = "or"
    max_expansions: int = 50
    minimum_should_match: Optional[str] = None
    analyzer: Optional[str] = None
    fuzziness: Optional[str] = None


@dataclass
class GeoPolygonQuery(Query):
    field: str = ""
    points: list = dc_field(default_factory=list)   # [(lat, lon)]


@dataclass
class RankFeatureQuery(Query):
    """Score by a per-doc feature value (modules/mapper-extras
    RankFeatureQueryBuilder): saturation (default), log, or sigmoid."""

    field: str = ""
    saturation: Optional[dict] = None
    log: Optional[dict] = None
    sigmoid: Optional[dict] = None


@dataclass
class MultiMatchQuery(Query):
    fields: list = dc_field(default_factory=list)   # [(field, boost)]
    query: Any = None
    type: str = "best_fields"        # best_fields | most_fields | phrase
    operator: str = "or"
    tie_breaker: float = 0.0
    minimum_should_match: Optional[str] = None
    lenient: bool = False
    analyzer: Optional[str] = None
    fuzziness: Optional[str] = None


@dataclass
class BoolQuery(Query):
    must: list = dc_field(default_factory=list)
    should: list = dc_field(default_factory=list)
    must_not: list = dc_field(default_factory=list)
    filter: list = dc_field(default_factory=list)
    minimum_should_match: Optional[str] = None


@dataclass
class RangeQuery(Query):
    field: str = ""
    gte: Any = None
    gt: Any = None
    lte: Any = None
    lt: Any = None
    fmt: Optional[str] = None
    time_zone: Optional[str] = None
    lenient: bool = False           # query_string lenient: bad bound -> none


@dataclass
class ExistsQuery(Query):
    field: str = ""


@dataclass
class IdsQuery(Query):
    values: list = dc_field(default_factory=list)


@dataclass
class PrefixQuery(Query):
    field: str = ""
    value: str = ""


@dataclass
class WildcardQuery(Query):
    field: str = ""
    value: str = ""
    case_insensitive: bool = False  # query_string wildcards normalize
    # through the analyzer chain (lowercase); the plain wildcard query
    # is exact unless case_insensitive is set


@dataclass
class RegexpQuery(Query):
    field: str = ""
    value: str = ""


@dataclass
class FuzzyQuery(Query):
    field: str = ""
    value: str = ""
    fuzziness: str = "AUTO"
    prefix_length: int = 0


@dataclass
class ConstantScoreQuery(Query):
    query: Optional[Query] = None


@dataclass
class DisMaxQuery(Query):
    queries: list = dc_field(default_factory=list)
    tie_breaker: float = 0.0


@dataclass
class KnnQuery(Query):
    field: str = ""
    vector: list = dc_field(default_factory=list)
    k: int = 10
    filter: Optional[Query] = None
    # per-request ANN overrides, e.g. {"nprobe": 16} (method_parameters
    # in the opensearch-knn request shape)
    method_parameters: Optional[dict] = None


@dataclass
class PercolateQuery(Query):
    field: str = "query"
    documents: list = dc_field(default_factory=list)   # candidate docs


@dataclass
class NestedQuery(Query):
    path: str = ""
    query: Optional[Query] = None
    score_mode: str = "avg"
    ignore_unmapped: bool = False


@dataclass
class HasChildQuery(Query):
    """Parents with >= min matching children (modules/parent-join/
    HasChildQueryBuilder.java)."""

    type: str = ""
    query: Optional[Query] = None
    score_mode: str = "none"        # none | sum | max | min | avg
    min_children: int = 1
    max_children: Optional[int] = None


@dataclass
class HasParentQuery(Query):
    """Children whose parent matches (HasParentQueryBuilder.java)."""

    parent_type: str = ""
    query: Optional[Query] = None
    score: bool = False


@dataclass
class ParentIdQuery(Query):
    """Children of one specific parent (ParentIdQueryBuilder.java)."""

    type: str = ""
    id: str = ""


@dataclass
class BoostingQuery(Query):
    positive: Optional[Query] = None
    negative: Optional[Query] = None
    negative_boost: float = 0.5


@dataclass
class TermsSetQuery(Query):
    field: str = ""
    terms: list = dc_field(default_factory=list)
    minimum_should_match_field: str = ""


@dataclass
class DistanceFeatureQuery(Query):
    field: str = ""
    origin: object = None
    pivot: object = None


@dataclass
class FunctionScoreQuery(Query):
    query: Optional[Query] = None
    functions: list = dc_field(default_factory=list)   # raw function dicts
    score_mode: str = "multiply"
    boost_mode: str = "multiply"
    max_boost: Optional[float] = None
    min_score: Optional[float] = None


@dataclass
class MoreLikeThisQuery(Query):
    fields: list = dc_field(default_factory=list)
    like: list = dc_field(default_factory=list)        # texts and {_id} docs
    max_query_terms: int = 25
    min_term_freq: int = 2
    min_doc_freq: int = 5
    minimum_should_match: str = "30%"
    include: bool = False          # include the liked docs in results


@dataclass
class GeoDistanceQuery(Query):
    field: str = ""
    lat: float = 0.0
    lon: float = 0.0
    distance: str = "10km"


@dataclass
class GeoBoundingBoxQuery(Query):
    field: str = ""
    top: float = 0.0
    left: float = 0.0
    bottom: float = 0.0
    right: float = 0.0


@dataclass
class HybridQuery(Query):
    """Independent sub-queries whose scores a search pipeline's
    normalization processor combines (the neural-search plugin's hybrid
    query; executes per sub-query, never as one plan)."""

    queries: list = dc_field(default_factory=list)


@dataclass
class SpanTermQuery(Query):
    """Positional term (ref index/query/SpanTermQueryBuilder.java:48)."""

    field: str = ""
    value: Any = None


@dataclass
class SpanNearQuery(Query):
    """Terms within ``slop`` positions of each other (ref
    SpanNearQueryBuilder.java:51)."""

    clauses: list = dc_field(default_factory=list)
    slop: int = 0
    in_order: bool = True


@dataclass
class SpanFirstQuery(Query):
    """Match near the start of the field (ref
    SpanFirstQueryBuilder.java:47)."""

    match: Optional[Query] = None
    end: int = 0


@dataclass
class SpanOrQuery(Query):
    """Union of span clauses (ref SpanOrQueryBuilder.java:46)."""

    clauses: list = dc_field(default_factory=list)


@dataclass
class IntervalsQuery(Query):
    """Interval rules over one field (ref IntervalQueryBuilder.java:43);
    the rule tree is validated/compiled per shard."""

    field: str = ""
    rule: dict = dc_field(default_factory=dict)


@dataclass
class ScriptScoreQuery(Query):
    query: Optional[Query] = None
    script: dict = dc_field(default_factory=dict)
    min_score: Optional[float] = None


@dataclass
class SimpleQueryStringQuery(Query):
    query: str = ""
    fields: list = dc_field(default_factory=list)
    default_operator: str = "or"


def _field_kv(body: dict, qname: str) -> tuple[str, Any]:
    if len(body) != 1:
        raise ParsingError(f"[{qname}] query must reference exactly one field, got {sorted(body)}")
    return next(iter(body.items()))


def _as_list(v) -> list:
    return v if isinstance(v, list) else [v]


def _boost(body) -> float:
    return float(body.get("boost", 1.0)) if isinstance(body, dict) else 1.0


def _parse_fields_with_boosts(fields: list) -> list[tuple[str, float]]:
    out = []
    for f in fields:
        if "^" in f:
            name, _, b = f.partition("^")
            out.append((name, float(b)))
        else:
            out.append((f, 1.0))
    return out


def parse_query(obj: Optional[dict]) -> Query:
    """Parse one query object ``{"<type>": {...}}`` into a Query tree."""
    if obj is None:
        return MatchAllQuery()
    if not isinstance(obj, dict):
        raise ParsingError(f"malformed query, expected an object but got [{obj}]")
    if not obj:
        return MatchAllQuery()
    if len(obj) != 1:
        raise ParsingError(
            f"malformed query, expected one top-level key but got {sorted(obj)}")
    qname, body = next(iter(obj.items()))
    parser = _PARSERS.get(qname)
    if parser is None:
        raise ParsingError(f"unknown query [{qname}]")
    return parser(body)


def _parse_match_all(body):
    return MatchAllQuery(boost=_boost(body))


def _parse_match_none(body):
    return MatchNoneQuery()


def _parse_term(body):
    field, v = _field_kv(body, "term")
    if isinstance(v, dict):
        return TermQuery(field=field, value=v.get("value"), boost=_boost(v))
    return TermQuery(field=field, value=v)


def _parse_terms(body):
    rest = {k: v for k, v in body.items() if k != "boost"}
    field, vals = _field_kv(rest, "terms")
    if not isinstance(vals, list):
        raise ParsingError("[terms] query requires an array of values")
    return TermsQuery(field=field, values=vals, boost=_boost(body))


def _parse_match(body):
    field, v = _field_kv(body, "match")
    if isinstance(v, dict):
        return MatchQuery(
            field=field, query=v.get("query"),
            operator=str(v.get("operator", "or")).lower(),
            minimum_should_match=(
                None if v.get("minimum_should_match") is None
                else str(v.get("minimum_should_match"))),
            fuzziness=v.get("fuzziness"),
            analyzer=v.get("analyzer"),
            boost=_boost(v))
    return MatchQuery(field=field, query=v)


def _parse_match_phrase(body):
    field, v = _field_kv(body, "match_phrase")
    if isinstance(v, dict):
        return MatchPhraseQuery(field=field, query=v.get("query"),
                                slop=int(v.get("slop", 0)), boost=_boost(v))
    return MatchPhraseQuery(field=field, query=v)


def _parse_multi_match(body):
    typ = str(body.get("type", "best_fields"))
    tie = body.get("tie_breaker")
    return MultiMatchQuery(
        fields=_parse_fields_with_boosts(body.get("fields", [])),
        query=body.get("query"),
        type=typ,
        operator=str(body.get("operator", "or")).lower(),
        tie_breaker=float(tie) if tie is not None else (1.0 if typ == "most_fields" else 0.0),
        minimum_should_match=(
            None if body.get("minimum_should_match") is None
            else str(body.get("minimum_should_match"))),
        analyzer=body.get("analyzer"),
        fuzziness=(None if body.get("fuzziness") is None
                   else str(body.get("fuzziness"))),
        boost=_boost(body))


def _parse_bool(body):
    msm = body.get("minimum_should_match")
    return BoolQuery(
        must=[parse_query(q) for q in _as_list(body.get("must", []))],
        should=[parse_query(q) for q in _as_list(body.get("should", []))],
        must_not=[parse_query(q) for q in _as_list(body.get("must_not", []))],
        filter=[parse_query(q) for q in _as_list(body.get("filter", []))],
        minimum_should_match=None if msm is None else str(msm),
        boost=_boost(body))


def _parse_range(body):
    field, v = _field_kv(body, "range")
    if not isinstance(v, dict):
        raise ParsingError("[range] query requires bounds object")
    known = {"gte", "gt", "lte", "lt", "from", "to", "include_lower",
             "include_upper", "boost", "format", "time_zone", "relation"}
    unknown = set(v) - known
    if unknown:
        raise ParsingError(f"[range] query does not support {sorted(unknown)}")
    gte, gt, lte, lt = v.get("gte"), v.get("gt"), v.get("lte"), v.get("lt")
    # legacy from/to form
    if "from" in v:
        if v.get("include_lower", True):
            gte = v["from"]
        else:
            gt = v["from"]
    if "to" in v:
        if v.get("include_upper", True):
            lte = v["to"]
        else:
            lt = v["to"]
    return RangeQuery(field=field, gte=gte, gt=gt, lte=lte, lt=lt,
                      fmt=v.get("format"), time_zone=v.get("time_zone"),
                      boost=_boost(v))


def _parse_exists(body):
    return ExistsQuery(field=body["field"], boost=_boost(body))


def _parse_ids(body):
    return IdsQuery(values=list(body.get("values", [])), boost=_boost(body))


def _term_like(cls, qname):
    def parse(body):
        field, v = _field_kv(body, qname)
        if isinstance(v, dict):
            return cls(field=field, value=v.get("value"), boost=_boost(v))
        return cls(field=field, value=v)
    return parse


def _parse_fuzzy(body):
    field, v = _field_kv(body, "fuzzy")
    if isinstance(v, dict):
        return FuzzyQuery(field=field, value=str(v.get("value")),
                          fuzziness=str(v.get("fuzziness", "AUTO")),
                          prefix_length=int(v.get("prefix_length", 0)),
                          boost=_boost(v))
    return FuzzyQuery(field=field, value=str(v))


def _parse_constant_score(body):
    return ConstantScoreQuery(query=parse_query(body.get("filter")), boost=_boost(body))


def _parse_dis_max(body):
    return DisMaxQuery(queries=[parse_query(q) for q in body.get("queries", [])],
                       tie_breaker=float(body.get("tie_breaker", 0.0)),
                       boost=_boost(body))


def _parse_knn(body):
    # Accept both the opensearch-knn plugin shape {field: {vector, k}} and a
    # flat {field, query_vector, k} shape.
    if "field" in body and ("query_vector" in body or "vector" in body):
        return KnnQuery(field=body["field"],
                        vector=list(body.get("query_vector") or body.get("vector")),
                        k=int(body.get("k", 10)),
                        filter=parse_query(body["filter"]) if body.get("filter") else None,
                        method_parameters=body.get("method_parameters"),
                        boost=_boost(body))
    field, v = _field_kv({k: v for k, v in body.items() if k != "boost"}, "knn")
    return KnnQuery(field=field, vector=list(v["vector"]), k=int(v.get("k", 10)),
                    filter=parse_query(v["filter"]) if v.get("filter") else None,
                    method_parameters=v.get("method_parameters"),
                    boost=_boost(v))


def parse_geo_point(v) -> tuple[float, float]:
    """(lat, lon) from the accepted geo shapes: {lat, lon}, [lon, lat],
    "lat,lon"."""
    if isinstance(v, dict):
        return float(v["lat"]), float(v["lon"])
    if isinstance(v, (list, tuple)) and len(v) == 2:
        return float(v[1]), float(v[0])            # GeoJSON order
    if isinstance(v, str) and "," in v:
        lat, _, lon = v.partition(",")
        return float(lat), float(lon)
    raise ParsingError(f"malformed geo point [{v!r}]")


_DIST_UNITS = {"mm": 0.001, "cm": 0.01, "m": 1.0, "km": 1000.0,
               "in": 0.0254, "ft": 0.3048, "yd": 0.9144,
               "mi": 1609.344, "nmi": 1852.0, "nauticalmiles": 1852.0,
               "kilometers": 1000.0, "meters": 1.0, "miles": 1609.344}


def parse_distance_m(v) -> float:
    """Distance expression -> meters ("10km", "5mi", bare number=m)."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip().lower()
    for unit in sorted(_DIST_UNITS, key=len, reverse=True):
        if s.endswith(unit):
            return float(s[: -len(unit)]) * _DIST_UNITS[unit]
    try:
        return float(s)
    except ValueError:
        raise ParsingError(f"failed to parse distance [{v}]") from None


def _parse_percolate(body):
    docs = body.get("documents")
    if docs is None and body.get("document") is not None:
        docs = [body["document"]]
    if not docs:
        raise ParsingError(
            "[percolate] requires [document] or [documents]")
    if not all(isinstance(d, dict) for d in docs):
        raise ParsingError(
            "[percolate] documents must be JSON objects")
    return PercolateQuery(field=str(body.get("field", "query")),
                          documents=list(docs), boost=_boost(body))


def _parse_match_phrase_prefix(body):
    field, v = _field_kv(body, "match_phrase_prefix")
    if isinstance(v, dict):
        return MatchPhrasePrefixQuery(
            field=field, query=v.get("query"),
            slop=int(v.get("slop", 0)),
            max_expansions=int(v.get("max_expansions", 50)),
            boost=_boost(v))
    return MatchPhrasePrefixQuery(field=field, query=v)


def _parse_match_bool_prefix(body):
    field, v = _field_kv(body, "match_bool_prefix")
    if isinstance(v, dict):
        return MatchBoolPrefixQuery(
            field=field, query=v.get("query"),
            operator=str(v.get("operator", "or")).lower(),
            max_expansions=int(v.get("max_expansions", 50)),
            minimum_should_match=v.get("minimum_should_match"),
            analyzer=v.get("analyzer"),
            fuzziness=(None if v.get("fuzziness") is None
                       else str(v.get("fuzziness"))),
            boost=_boost(v))
    return MatchBoolPrefixQuery(field=field, query=v)


def _parse_wrapper(body):
    """wrapper: {query: <base64 of a JSON query>} — decodes and parses
    inline (WrapperQueryBuilder)."""
    import base64
    import json as _json

    raw = body.get("query")
    if raw is None:
        raise ParsingError("[wrapper] requires [query]")
    try:
        inner = _json.loads(base64.b64decode(raw))
    except Exception as e:  # noqa: BLE001 — any malformed payload is a 400
        raise ParsingError(f"[wrapper] cannot decode query: {e}") from None
    return parse_query(inner)


def _parse_geo_polygon(body):
    field = next((k for k in body if k not in ("boost", "_name",
                                               "validation_method")), None)
    if field is None or not isinstance(body[field], dict):
        raise ParsingError("[geo_polygon] requires a field with [points]")
    pts = body[field].get("points")
    if not pts or len(pts) < 3:
        raise ParsingError("[geo_polygon] requires at least 3 [points]")
    points = []
    for p in pts:
        try:
            if isinstance(p, dict):
                points.append((float(p["lat"]), float(p["lon"])))
            elif isinstance(p, (list, tuple)):
                points.append((float(p[1]), float(p[0])))   # [lon, lat]
            elif isinstance(p, str) and "," in p:
                lat, _, lon = p.partition(",")
                points.append((float(lat), float(lon)))
            else:
                raise ParsingError(
                    f"[geo_polygon] malformed point {p!r} (lat/lon "
                    "object, [lon, lat] array, or 'lat,lon' string; "
                    "geohash points are not supported)")
        except ParsingError:
            raise
        except (KeyError, ValueError, TypeError, IndexError) as e:
            raise ParsingError(
                f"[geo_polygon] malformed point {p!r}: {e}") from None
    return GeoPolygonQuery(field=field, points=points, boost=_boost(body))


def _parse_rank_feature(body):
    field = body.get("field")
    if not field:
        raise ParsingError("[rank_feature] requires [field]")
    return RankFeatureQuery(field=str(field),
                            saturation=body.get("saturation"),
                            log=body.get("log"),
                            sigmoid=body.get("sigmoid"),
                            boost=_boost(body))


def _parse_has_child(body):
    if not body.get("type") or body.get("query") is None:
        raise ParsingError("[has_child] requires [type] and [query]")
    mx = body.get("max_children")
    return HasChildQuery(type=str(body["type"]),
                         query=parse_query(body["query"]),
                         score_mode=str(body.get("score_mode", "none")),
                         min_children=int(body.get("min_children", 1)),
                         max_children=None if mx is None else int(mx),
                         boost=_boost(body))


def _parse_has_parent(body):
    if not body.get("parent_type") or body.get("query") is None:
        raise ParsingError("[has_parent] requires [parent_type] and "
                           "[query]")
    return HasParentQuery(parent_type=str(body["parent_type"]),
                          query=parse_query(body["query"]),
                          score=bool(body.get("score", False)),
                          boost=_boost(body))


def _parse_parent_id(body):
    if not body.get("type") or body.get("id") is None:
        raise ParsingError("[parent_id] requires [type] and [id]")
    return ParentIdQuery(type=str(body["type"]), id=str(body["id"]),
                         boost=_boost(body))


def _parse_nested(body):
    if not body.get("path") or body.get("query") is None:
        raise ParsingError("[nested] requires [path] and [query]")
    return NestedQuery(path=str(body["path"]),
                       query=parse_query(body["query"]),
                       score_mode=str(body.get("score_mode", "avg")),
                       ignore_unmapped=bool(body.get("ignore_unmapped",
                                                     False)),
                       boost=_boost(body))


def _parse_boosting(body):
    if body.get("positive") is None or body.get("negative") is None:
        raise ParsingError(
            "[boosting] requires [positive] and [negative] clauses")
    return BoostingQuery(positive=parse_query(body["positive"]),
                         negative=parse_query(body["negative"]),
                         negative_boost=float(
                             body.get("negative_boost", 0.5)),
                         boost=_boost(body))


def _parse_terms_set(body):
    field, v = _field_kv({k: x for k, x in body.items() if k != "boost"},
                         "terms_set")
    msm = v.get("minimum_should_match_field")
    if not msm:
        raise ParsingError(
            "[terms_set] requires [minimum_should_match_field]")
    return TermsSetQuery(field=field, terms=list(v.get("terms") or []),
                         minimum_should_match_field=msm, boost=_boost(v))


def _parse_distance_feature(body):
    for key in ("field", "origin", "pivot"):
        if body.get(key) is None:
            raise ParsingError(f"[distance_feature] requires [{key}]")
    return DistanceFeatureQuery(field=body["field"], origin=body["origin"],
                                pivot=body["pivot"], boost=_boost(body))


_FUNCTION_KEYS = ("weight", "field_value_factor", "random_score",
                  "script_score", "gauss", "exp", "linear")


def _parse_function_score(body):
    functions = list(body.get("functions") or [])
    # single-function shorthand at the top level
    shorthand = {k: body[k] for k in _FUNCTION_KEYS if k in body}
    if shorthand:
        functions.append(shorthand)
    q = parse_query(body.get("query")) if body.get("query") else None
    return FunctionScoreQuery(
        query=q, functions=functions,
        score_mode=str(body.get("score_mode", "multiply")),
        boost_mode=str(body.get("boost_mode", "multiply")),
        max_boost=(float(body["max_boost"])
                   if body.get("max_boost") is not None else None),
        min_score=(float(body["min_score"])
                   if body.get("min_score") is not None else None),
        boost=_boost(body))


def _parse_more_like_this(body):
    like = body.get("like")
    if like is None:
        raise ParsingError("[more_like_this] requires [like]")
    if not isinstance(like, list):
        like = [like]
    return MoreLikeThisQuery(
        fields=list(body.get("fields") or []),
        like=like,
        max_query_terms=int(body.get("max_query_terms", 25)),
        min_term_freq=int(body.get("min_term_freq", 2)),
        min_doc_freq=int(body.get("min_doc_freq", 5)),
        minimum_should_match=str(body.get("minimum_should_match", "30%")),
        include=bool(body.get("include", False)),
        boost=_boost(body))


def _parse_geo_distance(body):
    dist = body.get("distance")
    if dist is None:
        raise ParsingError("[geo_distance] requires [distance]")
    field = next((k for k in body
                  if k not in ("distance", "boost", "distance_type",
                               "validation_method", "_name")), None)
    if field is None:
        raise ParsingError("[geo_distance] requires a field")
    lat, lon = parse_geo_point(body[field])
    parse_distance_m(dist)                  # validate eagerly
    return GeoDistanceQuery(field=field, lat=lat, lon=lon,
                            distance=dist, boost=_boost(body))


def _parse_geo_bounding_box(body):
    field = next((k for k in body
                  if k not in ("boost", "validation_method", "type",
                               "_name")), None)
    if field is None:
        raise ParsingError("[geo_bounding_box] requires a field")
    v = body[field]
    if "top_left" in v and "bottom_right" in v:
        top, left = parse_geo_point(v["top_left"])
        bottom, right = parse_geo_point(v["bottom_right"])
    else:
        top, left = float(v["top"]), float(v["left"])
        bottom, right = float(v["bottom"]), float(v["right"])
    if bottom > top:
        raise ParsingError(
            "[geo_bounding_box] top must be >= bottom")
    return GeoBoundingBoxQuery(field=field, top=top, left=left,
                               bottom=bottom, right=right,
                               boost=_boost(body))


# -- query_string ------------------------------------------------------------


_QS_TOKEN = re.compile(
    r"""\s*(?:
        (?P<lparen>\()|(?P<rparen>\))|
        (?P<and>AND\b|&&)|(?P<or>OR\b|\|\|)|(?P<not>NOT\b|!)|
        (?P<plus>\+)|(?P<minus>-)|
        (?P<quoted>"(?P<qbody>[^"]*)")|
        (?P<range>[\[{][^\]}]+(?:[\]}]))|
        (?P<word>[^\s()\[\]{}"]+)
    )""", re.VERBOSE)


def _qs_tokens(s: str):
    pos = 0
    out = []
    while pos < len(s):
        m = _QS_TOKEN.match(s, pos)
        if m is None or m.end() == pos:
            if s[pos:].strip():
                raise ParsingError(
                    f"query_string: cannot parse "
                    f"[{s[pos:].strip()[:40]}] — unbalanced quote or "
                    "stray bracket?")
            break
        out.append(m)
        pos = m.end()
    return out


class _QsParser:
    """Recursive-descent parser for the practical query_string subset:
    AND/OR/NOT (&&/||/!), +/-, parentheses, field:value, quoted phrases,
    wildcards, [a TO b]/{a TO b} ranges (QueryStringQueryBuilder's
    everyday surface; the exotic tail — fuzzy slop, boost suffixes,
    regex — parses as plain terms)."""

    def __init__(self, tokens, fields, default_operator):
        self.toks = tokens
        self.i = 0
        self.fields = fields
        self.default_and = default_operator == "and"

    def peek(self, name=None):
        if self.i >= len(self.toks):
            return None
        if name is None:
            return self.toks[self.i]
        return self.toks[self.i] if self.toks[self.i].group(name) else None

    def parse(self):
        q = self.or_expr()
        if self.i < len(self.toks):
            raise ParsingError(
                f"query_string: unexpected token "
                f"[{self.toks[self.i].group(0).strip()}]")
        return q or MatchAllQuery()

    def or_expr(self):
        parts = [self.and_expr()]
        while self.peek("or"):
            self.i += 1
            parts.append(self.and_expr())
        parts = [p for p in parts if p is not None]
        if len(parts) <= 1:
            return parts[0] if parts else None
        return BoolQuery(should=parts)

    def and_expr(self):
        must, must_not, should = [], [], []
        explicit_and = False
        while True:
            if self.peek("and"):
                self.i += 1
                explicit_and = True
                continue
            if self.peek("or") or self.peek("rparen") or \
                    self.peek() is None:
                break
            negate = False
            required = False
            if self.peek("not") or self.peek("minus"):
                self.i += 1
                negate = True
            elif self.peek("plus"):
                self.i += 1
                required = True
            clause = self.primary()
            if clause is None:
                break
            if negate:
                must_not.append(clause)
            elif required or self.default_and or explicit_and:
                must.append(clause)
            else:
                should.append(clause)
        if explicit_and or self.default_and:
            must.extend(should)
            should = []
        if not must and not must_not and len(should) == 1:
            return should[0]
        if not must and not must_not and not should:
            return None
        return BoolQuery(must=must, must_not=must_not, should=should)

    def primary(self):
        tok = self.peek()
        if tok is None:
            return None
        if tok.group("lparen"):
            self.i += 1
            inner = self.or_expr()
            if not self.peek("rparen"):
                raise ParsingError("query_string: unbalanced parentheses")
            self.i += 1
            return inner
        if tok.group("quoted") is not None:
            self.i += 1
            return self._text_clause(tok.group("qbody"), phrase=True)
        if tok.group("word"):
            word = tok.group("word")
            self.i += 1
            if word.endswith(":"):          # field: followed by ( or "
                field = word[:-1]
                return self._fielded(field)
            if ":" in word:
                field, _, value = word.partition(":")
                return self._value_clause(field, value)
            return self._text_clause(word, phrase=False)
        if tok.group("range"):
            raise ParsingError(
                "query_string: a range requires a field (field:[a TO b])")
        return None

    def _fielded(self, field):
        tok = self.peek()
        if tok is None:
            raise ParsingError(
                f"query_string: dangling field [{field}:]")
        if tok.group("quoted") is not None:
            self.i += 1
            return MatchPhraseQuery(field=field, query=tok.group("qbody"))
        if tok.group("range"):
            self.i += 1
            return self._range_clause(field, tok.group("range"))
        if tok.group("lparen"):
            self.i += 1
            inner = self.or_expr()
            if not self.peek("rparen"):
                raise ParsingError("query_string: unbalanced parentheses")
            self.i += 1
            return _rewrite_default_field(inner, field)
        if tok.group("word"):
            self.i += 1
            return self._value_clause(field, tok.group("word"))
        raise ParsingError(f"query_string: bad value for [{field}]")

    def _range_clause(self, field, raw):
        inc_lo = raw[0] == "["
        inc_hi = raw[-1] == "]"
        body = raw[1:-1]
        lo, _, hi = body.partition(" TO ")
        if not _:
            raise ParsingError(
                f"query_string: malformed range [{raw}]")
        params = {}
        if lo.strip() not in ("*", ""):
            params["gte" if inc_lo else "gt"] = lo.strip()
        if hi.strip() not in ("*", ""):
            params["lte" if inc_hi else "lt"] = hi.strip()
        return RangeQuery(field=field, **params)

    def _value_clause(self, field, value):
        if "*" in value or "?" in value:
            return WildcardQuery(field=field, value=value,
                                 case_insensitive=True)
        return MatchQuery(field=field, query=value)

    def _text_clause(self, text, phrase):
        if len(self.fields) == 1 and self.fields[0][0] != "*":
            f, fboost = self.fields[0]
            q = (MatchPhraseQuery(field=f, query=text) if phrase
                 else self._value_clause(f, text))
            q.boost = q.boost * fboost
            return q
        return MultiMatchQuery(fields=list(self.fields), query=text,
                               type="phrase" if phrase else "best_fields")


def _rewrite_default_field(q, field):
    """Apply field:(...) grouping: rewrite default-field clauses inside."""
    if isinstance(q, BoolQuery):
        return BoolQuery(
            must=[_rewrite_default_field(c, field) for c in q.must],
            should=[_rewrite_default_field(c, field) for c in q.should],
            must_not=[_rewrite_default_field(c, field)
                      for c in q.must_not],
            filter=[_rewrite_default_field(c, field) for c in q.filter],
            boost=q.boost)
    if isinstance(q, MultiMatchQuery):
        if q.type == "phrase":
            return MatchPhraseQuery(field=field, query=q.query)
        if "*" in q.query or "?" in q.query:
            return WildcardQuery(field=field, value=q.query,
                                 case_insensitive=True)
        return MatchQuery(field=field, query=q.query)
    return q


def _parse_query_string(body):
    text = body.get("query")
    if text is None:
        raise ParsingError("[query_string] requires [query]")
    fields = body.get("fields")
    if not fields:
        df = body.get("default_field", "*")
        fields = [df]
    fields = _parse_fields_with_boosts(fields)   # keep ^boost suffixes
    op = str(body.get("default_operator", "or")).lower()
    q = _QsParser(_qs_tokens(str(text)), fields, op).parse()
    if body.get("lenient"):
        _mark_lenient(q)
    b = _boost(body)
    if b != 1.0:
        q.boost = q.boost * b
    return q


def _mark_lenient(q):
    """lenient=true: type-mismatch clauses match nothing instead of
    erroring (QueryStringQueryParser.setLenient)."""
    if isinstance(q, (MatchQuery, MultiMatchQuery, RangeQuery)):
        q.lenient = True
    elif isinstance(q, BoolQuery):
        for group in (q.must, q.should, q.must_not, q.filter):
            for c in group:
                _mark_lenient(c)


def _parse_hybrid(body):
    qs = body.get("queries")
    if not isinstance(qs, list) or not qs:
        raise ParsingError("[hybrid] query requires a [queries] array")
    if len(qs) > 5:
        raise ParsingError("[hybrid] supports at most 5 sub-queries")
    return HybridQuery(queries=[parse_query(q) for q in qs],
                       boost=_boost(body))


def _parse_script_score(body):
    ms = body.get("min_score")
    return ScriptScoreQuery(query=parse_query(body.get("query")),
                            script=body.get("script", {}),
                            min_score=float(ms) if ms is not None else None,
                            boost=_boost(body))


def _parse_span_term(body):
    field, v = _field_kv(body, "span_term")
    if isinstance(v, dict):
        return SpanTermQuery(field=field, value=v.get("value"),
                             boost=float(v.get("boost", 1.0)))
    return SpanTermQuery(field=field, value=v)


def _parse_span_near(body):
    clauses = [parse_query(c) for c in body.get("clauses") or []]
    if not clauses:
        raise ParsingError("[span_near] requires [clauses]")
    return SpanNearQuery(clauses=clauses,
                         slop=int(body.get("slop", 0)),
                         in_order=bool(body.get("in_order", True)),
                         boost=_boost(body))


def _parse_span_first(body):
    if "match" not in body or "end" not in body:
        raise ParsingError("[span_first] requires [match] and [end]")
    return SpanFirstQuery(match=parse_query(body["match"]),
                          end=int(body["end"]), boost=_boost(body))


def _parse_span_or(body):
    clauses = [parse_query(c) for c in body.get("clauses") or []]
    if not clauses:
        raise ParsingError("[span_or] requires [clauses]")
    return SpanOrQuery(clauses=clauses, boost=_boost(body))


def _parse_intervals(body):
    field, rule = _field_kv(body, "intervals")
    if not isinstance(rule, dict) or len(rule) == 0:
        raise ParsingError(f"[intervals] on [{field}] requires a rule")
    return IntervalsQuery(field=field, rule=rule)


def _parse_simple_query_string(body):
    return SimpleQueryStringQuery(
        query=str(body.get("query", "")),
        fields=_parse_fields_with_boosts(body.get("fields", ["*"])),
        default_operator=str(body.get("default_operator", "or")).lower(),
        boost=_boost(body))


_PARSERS = {
    "match_all": _parse_match_all,
    "match_none": _parse_match_none,
    "term": _parse_term,
    "terms": _parse_terms,
    "match": _parse_match,
    "match_phrase": _parse_match_phrase,
    "multi_match": _parse_multi_match,
    "bool": _parse_bool,
    "range": _parse_range,
    "exists": _parse_exists,
    "ids": _parse_ids,
    "has_child": _parse_has_child,
    "has_parent": _parse_has_parent,
    "parent_id": _parse_parent_id,
    "match_phrase_prefix": _parse_match_phrase_prefix,
    "match_bool_prefix": _parse_match_bool_prefix,
    "wrapper": _parse_wrapper,
    "geo_polygon": _parse_geo_polygon,
    "rank_feature": _parse_rank_feature,
    "prefix": _term_like(PrefixQuery, "prefix"),
    "wildcard": _term_like(WildcardQuery, "wildcard"),
    "regexp": _term_like(RegexpQuery, "regexp"),
    "fuzzy": _parse_fuzzy,
    "constant_score": _parse_constant_score,
    "dis_max": _parse_dis_max,
    "knn": _parse_knn,
    "script_score": _parse_script_score,
    "hybrid": _parse_hybrid,
    "boosting": _parse_boosting,
    "nested": _parse_nested,
    "percolate": _parse_percolate,
    "terms_set": _parse_terms_set,
    "distance_feature": _parse_distance_feature,
    "function_score": _parse_function_score,
    "more_like_this": _parse_more_like_this,
    "geo_distance": _parse_geo_distance,
    "geo_bounding_box": _parse_geo_bounding_box,
    "query_string": _parse_query_string,
    "simple_query_string": _parse_simple_query_string,
    "span_term": _parse_span_term,
    "span_near": _parse_span_near,
    "span_first": _parse_span_first,
    "span_or": _parse_span_or,
    "intervals": _parse_intervals,
}
