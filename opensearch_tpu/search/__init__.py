from opensearch_tpu.search.query_dsl import parse_query  # noqa: F401
