"""Query compilation: Query tree -> (plan, bindings) -> jit'd per-segment
XLA program.

Analog of the reference's two-step ``QueryBuilder.rewrite`` +
``toQuery(QueryShardContext)`` (index/query/QueryShardContext.java:95) and
the Lucene ``Weight``/``Scorer`` machinery it produces.  The TPU twist:

- a *plan node* is a frozen, hashable dataclass holding only static
  STRUCTURE (field names, clause layout, scoring flags).  It is a jit
  static argument, so each distinct query SHAPE compiles once; all queries
  of that shape (any terms, bounds, boosts) reuse the compiled program;
- per-query compile-time data (term strings, idfs, bounds, boosts) lives
  in a parallel *bindings tree* mirroring the plan tree, consumed host-side
  by ``prepare`` which emits the dynamic ``ins`` pytree per segment;
- per-segment static sizes (gather budgets, padded term counts) travel as
  the ``dims`` tuple pytree, also static (bucketed pow2 so segments of
  similar size share programs);
- every node evaluates to ``(scores f32 [n_pad], matched bool [n_pad])``;
  scores are zero wherever unmatched, so boolean composition is masked
  arithmetic, not iterator intersection (Lucene ConjunctionDISI analog).
"""

from __future__ import annotations

import bisect
import fnmatch
import math
import re
from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

import opensearch_tpu.common.jaxenv  # noqa: F401
import jax
import jax.numpy as jnp
from jax import lax

from opensearch_tpu.index.segment import (LONG_MISSING_MAX, pad_bucket,
                                           pad_pow2)
from opensearch_tpu.ops import bm25 as bm25_ops
from opensearch_tpu.ops import filters as filter_ops
from opensearch_tpu.ops import phrase as phrase_ops
from opensearch_tpu.ops import quantized as quantized_ops
from opensearch_tpu.ops import span as span_ops

_I32 = np.int32
_F32 = np.float32


def _scalar(x, dtype):
    return jnp.asarray(np.asarray(x, dtype=dtype))  # staging-ok: per-query input (prep-cache owned)


def _pad_np(arr, size, fill, dtype):
    out = np.full(size, fill, dtype=dtype)
    a = np.asarray(arr, dtype=dtype)
    out[: len(a)] = a
    return jnp.asarray(out)  # staging-ok: per-query input (prep-cache owned)


# ---------------------------------------------------------------------------
# Plan nodes.  All frozen + hashable: static query structure only.
# Each implements:
#   arrays() -> frozenset[(group, field)]         device arrays needed
#   prepare(bind, seg, dseg, ctx) -> (dims, ins)  host-side, per segment
#   eval(A, dims, ins) -> (scores, matched)       traced, pure jnp
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Plan:
    def arrays(self) -> frozenset:
        return frozenset()

    def can_match(self, bind, seg) -> bool:
        """Host-side pre-filter: False only when NO doc in this segment
        can match (the CanMatchPreFilterSearchPhase analog, ref
        action/search/CanMatchPreFilterSearchPhase.java:73) — segments
        that can't match never dispatch a device program.  Must stay
        conservative: returning True is always safe."""
        return True

    def skip_arrays(self, dims) -> frozenset:
        """Subset of ``arrays()`` this plan does NOT need fully staged
        for the dims ``prepare`` returned — the executor passes it to
        ``build_arrays`` so a quantized lowering (which carries its
        compressed arrays through ``ins``) doesn't force the f32
        posting columns onto the device.  Composites keep the default
        (empty): only lowerings that opt in skip anything."""
        return frozenset()

    def max_score_bound(self, bind, seg) -> float:
        """Safe UPPER bound on any single doc's score in this segment —
        the MaxScore/BMW pruning surface over the per-term block-max
        impact metadata (``Segment.max_impacts``).  The executor skips
        segments whose bound cannot reach the min_score / running k-th
        score.  Returning ``math.inf`` (the default) is always safe;
        finite bounds carry a small multiplicative margin so float32
        kernel rounding can never make a real score exceed them."""
        return math.inf

    def describe(self, bind) -> str:
        """Compact structural description for the Profile API's query
        section (``Query.toString()`` analog): the plan's static fields
        plus bind cardinalities — never document data.  The profiler
        truncates to 200 chars, so nesting may clip."""
        import dataclasses
        parts = [f"{f.name}={getattr(self, f.name)!r}"
                 for f in dataclasses.fields(self)]
        if isinstance(bind, dict):
            for key in ("terms", "values"):
                v = bind.get(key)
                if isinstance(v, (list, tuple)) and v:
                    shown = ",".join(str(x) for x in v[:8])
                    more = ",…" if len(v) > 8 else ""
                    parts.append(f"{key}=[{shown}{more}]")
            for key in ("queries", "children"):
                v = bind.get(key)
                if isinstance(v, (list, tuple)):
                    parts.append(f"{key}#{len(v)}")
        return f"{type(self).__name__}({', '.join(parts)})"


# float32 kernel rounding can nudge a real score a few ulp above the
# float64 host-side bound arithmetic; inflating every finite bound by
# this factor keeps pruning strictly conservative.
_BOUND_MARGIN = 1.0001


def _boost_bound(self, bind, seg) -> float:
    """max_score_bound for constant-score plans: the boost IS the only
    possible score."""
    b = float(bind["boost"])
    return b * _BOUND_MARGIN if b >= 0 else math.inf


@dataclass(frozen=True)
class MatchAllPlan(Plan):
    def prepare(self, bind, seg, dseg, ctx):
        return (), (_scalar(bind["boost"], _F32),)

    def eval(self, A, dims, ins):
        (boost,) = ins
        n_pad = A["live"].shape[0]
        return jnp.full(n_pad, boost, jnp.float32), jnp.ones(n_pad, bool)

    max_score_bound = _boost_bound


@dataclass(frozen=True)
class MatchNonePlan(Plan):
    def prepare(self, bind, seg, dseg, ctx):
        return (), ()

    def eval(self, A, dims, ins):
        n_pad = A["live"].shape[0]
        return jnp.zeros(n_pad, jnp.float32), jnp.zeros(n_pad, bool)

    def max_score_bound(self, bind, seg) -> float:
        return 0.0


@dataclass(frozen=True)
class TermBagPlan(Plan):
    """Weighted bag of terms over one field's postings: term / match /
    terms-as-should.  BM25-scored (Lucene TermQuery / BooleanQuery of term
    clauses).  bind: {terms, idfs, weights, required}; ``required`` is the
    per-doc matched-clause count needed (1 = OR, n_terms = AND,
    minimum_should_match otherwise)."""

    field: str = ""
    scored: bool = True

    def arrays(self):
        return frozenset({("postings", self.field)})

    def can_match(self, bind, seg):
        pf = seg.postings.get(self.field)
        if pf is None:
            return False
        present = sum(1 for t in bind["terms"] if pf.term_id(t) >= 0)
        # a doc can match at most `present` distinct query terms here
        return present >= max(int(bind.get("required", 1)), 1)

    def max_score_bound(self, bind, seg):
        if not self.scored:
            return 0.0                   # filter context scores are 0
        pf = seg.postings.get(self.field)
        if pf is None:
            return 0.0
        mi = seg.max_impacts(self.field, bind["avgdl"])
        total = 0.0
        for t, idf_v, w in zip(bind["terms"], bind["idfs"],
                               bind["weights"]):
            if w < 0:
                return math.inf          # negative weights: no bound
            tid = pf.term_id(t)
            if tid >= 0:
                total += float(idf_v) * float(w) * float(mi[tid])
        return total * _BOUND_MARGIN

    def host_topk(self, bind, seg, live, k: int, min_score=None):
        """CPU-backend fast path: score this bag host-side from the
        segment's precomputed impact table (``Segment.impact_table``)
        and return ``(vals f32 [m<=k], idx i32 [m], total, max_score)``
        with ``run_topk``'s exact semantics — float32 contributions in
        the same multiply order as the device kernel, in-order per-term
        accumulation, live/min_score masking excluded from totals, and
        ``lax.top_k``'s tie-break (score desc, then LOWER doc id).

        Used instead of a device dispatch when
        ``bm25_ops.host_scoring_enabled()`` — see ops/bm25.py on why
        scatter-heavy scoring is lowered host-side on XLA:CPU."""
        n = seg.n_docs
        pf = seg.postings.get(self.field)
        if pf is None:
            return (np.empty(0, _F32), np.empty(0, _I32), 0, -np.inf)
        from opensearch_tpu.index import codec as codec_mod
        if codec_mod.use_quantized(seg):
            # parity with the QUANTIZED device kernel: reconstruct
            # impacts exactly as ops/quantized.py does (q * scale,
            # exact-guard blocks overridden) so budget-eviction /
            # breaker degradation stays byte-identical on compressed
            # segments too
            imp = seg.quantized_table(self.field,
                                      bind["avgdl"]).dequantized()
        else:
            imp, _mx = seg.impact_table(self.field, bind["avgdl"])
        idfs = np.asarray(bind["idfs"], _F32)
        weights = np.asarray(bind["weights"], _F32)
        required = int(bind["required"])
        fast = (required == 1 and bool((weights > 0).all())
                and bool((idfs > 0).all()))
        scores = np.zeros(n, _F32)
        counts = None if fast else np.zeros(n, np.int32)
        for t, idf_v, w in zip(bind["terms"], idfs, weights):
            tid = pf.term_id(t)
            if tid < 0:
                continue
            e0, e1 = int(pf.offsets[tid]), int(pf.offsets[tid + 1])
            d = pf.doc_ids[e0:e1]
            # doc ids are unique within one postings list: plain fancy-
            # index add accumulates in gather order, matching the
            # device scatter bit-for-bit
            scores[d] += w * (idf_v * imp[e0:e1])
            if counts is not None:
                counts[d] += 1
        matched = (scores > 0.0 if counts is None
                   else counts >= required)
        matched &= live[:n]
        if min_score is not None:
            matched &= scores >= np.float32(min_score)
        midx = np.flatnonzero(matched)
        total = len(midx)
        if total == 0:
            return (np.empty(0, _F32), np.empty(0, _I32), 0, -np.inf)
        mscores = scores[midx]
        mx = float(mscores.max())
        if total > k:
            kth = np.partition(mscores, -k)[-k]
            midx = midx[mscores >= kth]
        order = np.lexsort((midx, -scores[midx]))[:k]
        sel = midx[order]
        return scores[sel], sel.astype(_I32), total, mx

    def prepare(self, bind, seg, dseg, ctx):
        terms = bind["terms"]
        pf = seg.postings.get(self.field)
        t_pad = pad_pow2(len(terms), minimum=1)
        tids = np.zeros(t_pad, dtype=_I32)
        active = np.zeros(t_pad, dtype=bool)
        budget = 0
        for i, t in enumerate(terms):
            tid = pf.term_id(t) if pf is not None else -1
            if tid >= 0:
                tids[i] = tid
                active[i] = True
                budget += int(pf.df[tid])
        if not self.scored:
            ins = (jnp.asarray(tids), jnp.asarray(active),  # staging-ok: per-query input (prep-cache owned)
                   _scalar(bind["required"], _I32))
            return (t_pad, pad_bucket(budget), False), ins
        idfs = np.asarray(bind["idfs"], _F32)
        weights = np.asarray(bind["weights"], _F32)
        # fast path: a plain OR bag with positive idf*weight scores > 0
        # exactly on matched docs, so the matched-count scatter (half the
        # kernel's scatter traffic) is skipped entirely
        fast = (int(bind["required"]) == 1
                and bool((weights > 0).all()) and bool((idfs > 0).all()))
        if getattr(dseg, "quantized_mode", False):
            # QUANTIZED lowering (index/codec.py): the compressed
            # columns ride in ``ins`` via the pager, the f32 posting
            # arrays are never staged (see ``skip_arrays``), and dims
            # grows a 4th element — width is a static shape input to
            # the packed gather, and the arity keeps compiled f32
            # programs distinct from quantized ones.
            qarrs = dseg.quantized(self.field, bind["avgdl"])
            qt = seg.quantized_table(self.field, bind["avgdl"])
            ins = (jnp.asarray(tids), jnp.asarray(active),  # staging-ok: per-query input (prep-cache owned)
                   _pad_np(idfs, t_pad, 0.0, _F32),
                   _pad_np(weights, t_pad, 0.0, _F32),
                   qarrs["qvals"], qarrs["scales"],
                   qarrs["exact_vals"], qarrs["exact_offsets"],
                   qarrs["packed"], qarrs["base"],
                   _scalar(bind["required"], _I32))
            return (t_pad, pad_bucket(budget), fast, int(qt.width)), ins
        ins = (jnp.asarray(tids), jnp.asarray(active),  # staging-ok: per-query input (prep-cache owned)
               _pad_np(idfs, t_pad, 0.0, _F32),
               _pad_np(weights, t_pad, 0.0, _F32),
               dseg.impacts(self.field, bind["avgdl"]),  # quantize-ok: f32 lowering (non-quantized segments)
               _scalar(bind["required"], _I32))
        return (t_pad, pad_bucket(budget), fast), ins

    def skip_arrays(self, dims) -> frozenset:
        # 4-tuple dims = quantized lowering: eval only needs the
        # (always-staged) offsets from the postings entry, so the
        # executor must NOT demand-stage the full f32 columns
        if len(dims) == 4:
            return frozenset({("postings", self.field)})
        return frozenset()

    def prefetch_quantized(self, bind, segments) -> int:
        """Prefetch oracle for the pager: rank candidate segments by
        their per-term block-max score bound — the best any of their
        docs could contribute, exactly the MaxScore pruning surface —
        and prefetch quantized pages best-first into FREE pager
        capacity (never evicting residents).  Returns segments staged."""
        from opensearch_tpu.index import codec as codec_mod
        from opensearch_tpu.index.segment import prefetch_quantized
        ranked = []
        for seg in segments:
            if not codec_mod.use_quantized(seg):
                continue
            if not self.can_match(bind, seg):
                continue
            ranked.append((self.max_score_bound(bind, seg), seg))
        ranked.sort(key=lambda pair: -pair[0])
        staged = 0
        for _bound, seg in ranked:
            if prefetch_quantized(seg, self.field, bind["avgdl"]):
                staged += 1
        return staged

    def eval(self, A, dims, ins):
        p = A["postings"][self.field]
        n_pad = A["live"].shape[0]
        if self.scored and len(dims) == 4:
            t_pad, budget, fast, width = dims
            (tids, active, idfs, weights, qvals, scales, exact_vals,
             exact_offsets, packed, base, required) = ins
            if fast:
                scores = quantized_ops.quantized_impact_scores(  # engine-ok: TermBag quantized lowering
                    p["offsets"], packed, base, qvals, scales,
                    exact_vals, exact_offsets, tids, active, idfs,
                    weights, width=width, n_pad=n_pad, budget=budget)
                matched = scores > 0.0
            else:
                scores, count = quantized_ops.quantized_impact_score_count(  # engine-ok: TermBag quantized lowering
                    p["offsets"], packed, base, qvals, scales,
                    exact_vals, exact_offsets, tids, active, idfs,
                    weights, width=width, n_pad=n_pad, budget=budget,
                    scored=True)
                matched = count >= required
            return jnp.where(matched, scores, 0.0), matched
        t_pad, budget, fast = dims
        if not self.scored:
            tids, active, required = ins
            count = bm25_ops.match_count(  # engine-ok: TermBag filter lowering
                p["offsets"], p["doc_ids"], p["tfs"], tids, active,
                n_pad=n_pad, budget=budget)
            return jnp.zeros(n_pad, jnp.float32), count >= required
        tids, active, idfs, weights, impacts, required = ins
        if fast:
            scores = bm25_ops.impact_scores(  # engine-ok: TermBag scored lowering
                p["offsets"], p["doc_ids"], impacts, tids, active,
                idfs, weights, n_pad=n_pad, budget=budget)
            matched = scores > 0.0
        else:
            scores, count = bm25_ops.impact_score_count(  # engine-ok: TermBag scored lowering
                p["offsets"], p["doc_ids"], impacts, tids, active,
                idfs, weights, n_pad=n_pad, budget=budget, scored=True)
            matched = count >= required
        return jnp.where(matched, scores, 0.0), matched


@dataclass(frozen=True)
class PhrasePlan(Plan):
    """Exact phrase over one field (match_phrase, slop=0).  bind: {terms,
    positions, idf_sum, boost, avgdl}."""

    field: str = ""
    scored: bool = True

    def arrays(self):
        return frozenset({("postings", self.field)})

    def can_match(self, bind, seg):
        pf = seg.postings.get(self.field)
        if pf is None:
            return False
        # an exact phrase needs EVERY term present
        return all(pf.term_id(t) >= 0 for t in bind["terms"])

    def max_score_bound(self, bind, seg):
        if not self.scored:
            return 0.0
        # tf/(tf+norm) < 1 always (norm >= k1*(1-b) > 0)
        return (float(bind["idf_sum"]) * float(bind["boost"])
                * _BOUND_MARGIN)

    def prepare(self, bind, seg, dseg, ctx):
        terms = bind["terms"]
        pf = seg.postings.get(self.field)
        m = len(terms)
        tids = np.zeros(m, dtype=_I32)
        active = np.zeros(m, dtype=bool)
        budgets = []
        for j, t in enumerate(terms):
            tid = pf.term_id(t) if pf is not None else -1
            count = 0
            if tid >= 0:
                tids[j] = tid
                active[j] = True
                e0, e1 = int(pf.offsets[tid]), int(pf.offsets[tid + 1])
                count = int(pf.pos_offsets[e1] - pf.pos_offsets[e0])
            budgets.append(pad_bucket(count, minimum=1024))
        ins = (jnp.asarray(tids), jnp.asarray(active),  # staging-ok: per-query input (prep-cache owned)
               jnp.asarray(np.asarray(bind["positions"], _I32)),  # staging-ok: per-query input (prep-cache owned)
               _scalar(bind["idf_sum"], _F32),
               _scalar(bind["boost"], _F32),
               _scalar(bind["avgdl"], _F32))
        return (tuple(budgets),), ins

    def eval(self, A, dims, ins):
        (budgets,) = dims
        tids, active, positions, idf_sum, boost, avgdl = ins
        p = A["postings"][self.field]
        n_pad = A["live"].shape[0]
        tf = phrase_ops.phrase_freqs(
            p, tids, active, positions, budgets=budgets, n_pad=n_pad)
        matched = tf > 0
        if not self.scored:
            return jnp.zeros(n_pad, jnp.float32), matched
        dl = p["doc_lens"]
        norm = bm25_ops.K1_DEFAULT * (1.0 - bm25_ops.B_DEFAULT
                                      + bm25_ops.B_DEFAULT * dl / avgdl)
        scores = idf_sum * boost * tf / (tf + norm)
        return jnp.where(matched, scores, 0.0), matched


@dataclass(frozen=True)
class SpanNearPlan(Plan):
    """Span/interval proximity over one field (span_near, span_first,
    intervals match — ref SpanNearQueryBuilder.java:51,
    IntervalQueryBuilder.java:43).  bind: {terms, slop, end, idf_sum,
    boost, avgdl}; slop and end are dynamic scalars so tuning proximity
    never recompiles."""

    field: str = ""
    ordered: bool = True
    scored: bool = True

    def arrays(self):
        return frozenset({("postings", self.field)})

    def can_match(self, bind, seg):
        pf = seg.postings.get(self.field)
        if pf is None:
            return False
        return all(pf.term_id(t) >= 0 for t in bind["terms"])

    def max_score_bound(self, bind, seg):
        if not self.scored:
            return 0.0
        return (float(bind["idf_sum"]) * float(bind["boost"])
                * _BOUND_MARGIN)

    def prepare(self, bind, seg, dseg, ctx):
        terms = bind["terms"]
        pf = seg.postings.get(self.field)
        m = len(terms)
        tids = np.zeros(m, dtype=_I32)
        active = np.zeros(m, dtype=bool)
        budgets = []
        for j, t in enumerate(terms):
            tid = pf.term_id(t) if pf is not None else -1
            count = 0
            if tid >= 0:
                tids[j] = tid
                active[j] = True
                e0, e1 = int(pf.offsets[tid]), int(pf.offsets[tid + 1])
                count = int(pf.pos_offsets[e1] - pf.pos_offsets[e0])
            budgets.append(pad_bucket(count, minimum=1024))
        ins = (jnp.asarray(tids), jnp.asarray(active),  # staging-ok: per-query input (prep-cache owned)
               _scalar(bind["slop"], _I32), _scalar(bind["end"], _I32),
               _scalar(bind["idf_sum"], _F32),
               _scalar(bind["boost"], _F32),
               _scalar(bind["avgdl"], _F32))
        return (tuple(budgets),), ins

    def eval(self, A, dims, ins):
        (budgets,) = dims
        tids, active, slop, end, idf_sum, boost, avgdl = ins
        p = A["postings"][self.field]
        n_pad = A["live"].shape[0]
        tf = span_ops.span_near_freqs(
            p, tids, active, budgets=budgets, n_pad=n_pad,
            ordered=self.ordered, slop=slop, end=end)
        matched = tf > 0
        if not self.scored:
            return jnp.zeros(n_pad, jnp.float32), matched
        dl = p["doc_lens"]
        norm = bm25_ops.K1_DEFAULT * (1.0 - bm25_ops.B_DEFAULT
                                      + bm25_ops.B_DEFAULT * dl / avgdl)
        scores = idf_sum * boost * tf / (tf + norm)
        return jnp.where(matched, scores, 0.0), matched


@dataclass(frozen=True)
class NumericTermsPlan(Plan):
    """term/terms over a numeric/date column: constant score (the reference
    compiles these to point/doc-values queries under ConstantScore).
    bind: {values, boost}."""

    field: str = ""
    kind: str = "long"               # long | double

    def arrays(self):
        return frozenset({("numeric", self.field)})

    def prepare(self, bind, seg, dseg, ctx):
        vals = bind["values"]
        q_pad = pad_pow2(len(vals), minimum=1)
        dtype = np.int64 if self.kind == "long" else np.float64
        fill = LONG_MISSING_MAX if self.kind == "long" else np.nan
        qv = _pad_np(vals, q_pad, fill, dtype)
        qvalid = _pad_np(np.ones(len(vals), bool), q_pad, False, bool)
        return (q_pad,), (qv, qvalid, _scalar(bind["boost"], _F32))

    def eval(self, A, dims, ins):
        qv, qvalid, boost = ins
        col = A["numeric"][self.field]
        n_pad = A["live"].shape[0]
        ok = (col["values"][:, None] == qv[None, :]) & qvalid[None, :]
        matched = jnp.zeros(n_pad, bool).at[col["value_docs"]].max(ok.any(axis=1))
        return jnp.where(matched, boost, 0.0).astype(jnp.float32), matched


@dataclass(frozen=True)
class NumericRangePlan(Plan):
    """bind: {lo, hi, boost} (inclusivity resolved into the bounds at
    compile time for longs; kept as static flags for doubles)."""

    field: str = ""
    kind: str = "long"               # long | double
    include_lo: bool = True
    include_hi: bool = True

    def arrays(self):
        return frozenset({("numeric", self.field)})

    def can_match(self, bind, seg):
        dv = seg.numeric_dv.get(self.field)
        if dv is None or not len(dv.value_docs):
            return False
        bounds = getattr(dv, "_value_bounds", None)
        if bounds is None:
            # immutable per segment: one scan serves every query
            bounds = dv._value_bounds = (dv.values.min(), dv.values.max())
        seg_lo, seg_hi = bounds
        lo, hi = bind["lo"], bind["hi"]
        if (seg_hi < lo or (seg_hi == lo and not self.include_lo)
                or seg_lo > hi or (seg_lo == hi and not self.include_hi)):
            return False
        return True

    def prepare(self, bind, seg, dseg, ctx):
        dtype = np.int64 if self.kind == "long" else np.float64
        return (), (_scalar(bind["lo"], dtype), _scalar(bind["hi"], dtype),
                    _scalar(bind["boost"], _F32))

    def eval(self, A, dims, ins):
        lo, hi, boost = ins
        col = A["numeric"][self.field]
        n_pad = A["live"].shape[0]
        matched = filter_ops.range_mask(
            col["values"], col["value_docs"], lo, hi,
            include_lo=self.include_lo, include_hi=self.include_hi,
            n_pad=n_pad)
        return jnp.where(matched, boost, 0.0).astype(jnp.float32), matched


@dataclass(frozen=True)
class OrdinalRangePlan(Plan):
    """Keyword range: per-segment ordinal bounds resolved host-side by
    binary search over the sorted term dictionary; the device compares
    ordinals (ordinal order == term order by construction).
    bind: {lo, lo_incl, hi, hi_incl, boost}."""

    field: str = ""

    def arrays(self):
        return frozenset({("ordinal", self.field)})

    def prepare(self, bind, seg, dseg, ctx):
        dv = seg.ordinal_dv.get(self.field)
        terms = dv.ord_terms if dv is not None else []
        lo, hi = bind["lo"], bind["hi"]
        lo_ord = 0
        hi_ord = len(terms)
        if lo is not None:
            lo_ord = (bisect.bisect_left(terms, lo) if bind["lo_incl"]
                      else bisect.bisect_right(terms, lo))
        if hi is not None:
            hi_ord = (bisect.bisect_right(terms, hi) if bind["hi_incl"]
                      else bisect.bisect_left(terms, hi))
        return (), (_scalar(lo_ord, _I32), _scalar(hi_ord, _I32),
                    _scalar(bind["boost"], _F32))

    def eval(self, A, dims, ins):
        lo_ord, hi_ord, boost = ins
        col = A["ordinal"][self.field]
        n_pad = A["live"].shape[0]
        matched = filter_ops.range_mask(
            col["ords"], col["value_docs"], lo_ord, hi_ord,
            include_lo=True, include_hi=False, n_pad=n_pad)
        return jnp.where(matched, boost, 0.0).astype(jnp.float32), matched


@dataclass(frozen=True)
class PostingsMaskPlan(Plan):
    """Constant-score docs-containing-any-of-these-terms (terms query on a
    keyword/text field — Lucene TermInSetQuery).  bind: {terms, boost}."""

    field: str = ""

    def arrays(self):
        return frozenset({("postings", self.field)})

    def prepare(self, bind, seg, dseg, ctx):
        terms = bind["terms"]
        pf = seg.postings.get(self.field)
        t_pad = pad_pow2(len(terms), minimum=1)
        tids = np.zeros(t_pad, dtype=_I32)
        active = np.zeros(t_pad, dtype=bool)
        budget = 0
        for i, t in enumerate(terms):
            tid = pf.term_id(t) if pf is not None else -1
            if tid >= 0:
                tids[i] = tid
                active[i] = True
                budget += int(pf.df[tid])
        return ((t_pad, pad_bucket(budget)),
                (jnp.asarray(tids), jnp.asarray(active),  # staging-ok: per-query input (prep-cache owned)
                 _scalar(bind["boost"], _F32)))

    def eval(self, A, dims, ins):
        t_pad, budget = dims
        tids, active, boost = ins
        p = A["postings"][self.field]
        n_pad = A["live"].shape[0]
        matched = filter_ops.postings_mask(
            p["offsets"], p["doc_ids"], p["tfs"], tids, active,
            n_pad=n_pad, budget=budget)
        return jnp.where(matched, boost, 0.0).astype(jnp.float32), matched


@dataclass(frozen=True)
class TermRangeMaskPlan(Plan):
    """Constant-score docs containing any term in a CONTIGUOUS term-id
    range — a prefix is a range of the sorted term dict (Lucene
    PrefixQuery's automaton walk collapses to two binary searches).
    bind: {lo, hi, boost} (string bounds, [lo, hi))."""

    field: str = ""

    def arrays(self):
        return frozenset({("postings", self.field)})

    def prepare(self, bind, seg, dseg, ctx):
        pf = seg.postings.get(self.field)
        lo_tid = hi_tid = 0
        budget = 0
        if pf is not None:
            sterms = ctx.sorted_terms(seg, self.field)
            lo_tid = bisect.bisect_left(sterms, bind["lo"])
            hi_tid = bisect.bisect_left(sterms, bind["hi"])
            budget = int(pf.offsets[hi_tid] - pf.offsets[lo_tid])
        return ((pad_bucket(budget),),
                (_scalar(lo_tid, _I32), _scalar(hi_tid, _I32),
                 _scalar(bind["boost"], _F32)))

    def eval(self, A, dims, ins):
        (budget,) = dims
        lo_tid, hi_tid, boost = ins
        p = A["postings"][self.field]
        n_pad = A["live"].shape[0]
        o_lo = p["offsets"][lo_tid]
        o_hi = p["offsets"][hi_tid]
        i = jnp.arange(budget, dtype=jnp.int32)
        valid = i < (o_hi - o_lo)
        idx = jnp.where(valid, o_lo + i, 0)
        d = jnp.where(valid, p["doc_ids"][idx], n_pad - 1)
        matched = jnp.zeros(n_pad, bool).at[d].max(valid)
        return jnp.where(matched, boost, 0.0).astype(jnp.float32), matched


@dataclass(frozen=True)
class ExpandTermsPlan(Plan):
    """wildcard / regexp / fuzzy: terms enumerated host-side per segment
    against the sorted dictionary, then a constant-score postings mask
    (Lucene MultiTermQuery CONSTANT_SCORE rewrite).
    bind: {pattern, fuzzy_dist, prefix_length, boost}."""

    field: str = ""
    mode: str = "wildcard"           # wildcard | regexp | fuzzy

    def arrays(self):
        return frozenset({("postings", self.field)})

    def _expand(self, bind, sterms: list[str]) -> list[int]:
        pat = bind["pattern"]
        if self.mode == "wildcard":
            flags = re.IGNORECASE if bind.get("nocase") else 0
            rx = re.compile(fnmatch.translate(pat), flags)
            return [i for i, t in enumerate(sterms) if rx.match(t)]
        if self.mode == "regexp":
            rx = re.compile(pat)
            return [i for i, t in enumerate(sterms) if rx.fullmatch(t)]
        out = []
        pre = pat[: bind["prefix_length"]]
        for i, t in enumerate(sterms):
            if pre and not t.startswith(pre):
                continue
            if _edit_distance_le(pat, t, bind["fuzzy_dist"]):
                out.append(i)
        return out

    def prepare(self, bind, seg, dseg, ctx):
        pf = seg.postings.get(self.field)
        tids_list: list[int] = []
        budget = 0
        if pf is not None:
            sterms = ctx.sorted_terms(seg, self.field)
            tids_list = self._expand(bind, sterms)
            budget = int(sum(int(pf.df[t]) for t in tids_list))
        t_pad = pad_pow2(len(tids_list), minimum=1)
        return ((t_pad, pad_bucket(budget)),
                (_pad_np(tids_list, t_pad, 0, _I32),
                 _pad_np(np.ones(len(tids_list), bool), t_pad, False, bool),
                 _scalar(bind["boost"], _F32)))

    eval = PostingsMaskPlan.eval


@dataclass(frozen=True)
class ExistsPlan(Plan):
    field: str = ""
    src: str = "numeric"             # numeric | ordinal | vector | geo | norms

    def arrays(self):
        group = "postings" if self.src == "norms" else self.src
        return frozenset({(group, self.field)})

    def prepare(self, bind, seg, dseg, ctx):
        return (), (_scalar(bind["boost"], _F32),)

    def eval(self, A, dims, ins):
        (boost,) = ins
        if self.src == "norms":
            # the norms-entry analog: matches zero-token values too
            matched = A["postings"][self.field]["field_exists"]
        else:
            matched = A[self.src][self.field]["exists"]
        return jnp.where(matched, boost, 0.0).astype(jnp.float32), matched


@dataclass(frozen=True)
class MaskPlan(Plan):
    """Host-precomputed per-segment boolean mask (ids query).
    bind: {mask_fn: (seg, dseg) -> np.bool_[n_pad], boost}."""

    label: str = "ids"

    def prepare(self, bind, seg, dseg, ctx):
        mask = bind["mask_fn"](seg, dseg)
        return (), (jnp.asarray(mask), _scalar(bind["boost"], _F32))  # staging-ok: per-query input (prep-cache owned)

    def eval(self, A, dims, ins):
        mask, boost = ins
        return jnp.where(mask, boost, 0.0).astype(jnp.float32), mask


@dataclass(frozen=True)
class ScoredMaskPlan(Plan):
    """Precomputed per-segment (scores, matched) — knn pre-pass results are
    injected into the tree through this node.
    bind: {fn: (seg, dseg) -> (scores, mask)}."""

    label: str = "knn"

    def prepare(self, bind, seg, dseg, ctx):
        scores, mask = bind["fn"](seg, dseg)
        return (), (jnp.asarray(scores), jnp.asarray(mask))  # staging-ok: per-query input (prep-cache owned)

    def eval(self, A, dims, ins):
        scores, mask = ins
        return jnp.where(mask, scores, 0.0).astype(jnp.float32), mask


@dataclass(frozen=True)
class ScriptScorePlan(Plan):
    """Child plan scores re-mapped by a compiled script expression
    (ScriptScoreQuery; ref index/query/functionscore + the k-NN plugin's
    script-score path).  ``program`` is a scripting.ScriptProgram —
    hashable by (source, params), so identical scripts share one
    compiled XLA program per shape bucket."""

    child: Plan = None
    program: object = None

    def arrays(self):
        return self.child.arrays()

    def prepare(self, bind, seg, dseg, ctx):
        cdims, cins = self.child.prepare(bind["child"], seg, dseg, ctx)
        n_pad = dseg.n_pad
        ncols = []
        for f in self.program.numeric_fields:
            col = dseg.numeric.get(f)
            if col is None:
                ncols.append((jnp.zeros(n_pad, jnp.float32),
                              jnp.zeros(n_pad, bool)))
            else:
                # dense single-value view: min == the value for
                # single-valued fields; missing slots read 0.0
                vals = jnp.where(col["exists"],
                                 col["minv"].astype(jnp.float32), 0.0)
                ncols.append((vals, col["exists"]))
        vcols = []
        for f in self.program.vector_fields:
            vcol = dseg.vector.get(f)
            if vcol is None:
                from opensearch_tpu.search.scripting import ScriptException
                raise ScriptException(
                    f"script references vector field [{f}] with no "
                    "vectors in this index")
            vcols.append((vcol["values"], vcol["exists"]))
        return (cdims,), (cins, tuple(ncols), tuple(vcols),
                          self.program.param_values(),
                          _scalar(bind["boost"], _F32),
                          _scalar(bind.get("min_score")
                                  if bind.get("min_score") is not None
                                  else -np.inf, _F32))

    def eval(self, A, dims, ins):
        (cdims,) = dims
        cins, ncols, vcols, param_vals, boost, min_score = ins
        scores, matched = self.child.eval(A, cdims, cins)
        new = self.program.eval(
            scores,
            dict(zip(self.program.numeric_fields, ncols)),
            dict(zip(self.program.vector_fields, vcols)),
            param_vals)
        new = (jnp.broadcast_to(new, matched.shape)
               .astype(jnp.float32) * boost)
        matched = matched & (new >= min_score)
        return jnp.where(matched, new, 0.0), matched


def _prepare_children(children, binds, seg, dseg, ctx):
    dims, ins = [], []
    for c, b in zip(children, binds):
        d, i = c.prepare(b, seg, dseg, ctx)
        dims.append(d)
        ins.append(i)
    return tuple(dims), tuple(ins)


@dataclass(frozen=True)
class BoolPlan(Plan):
    """bind: {boost, required, children: tuple of child binds} where
    ``required`` is the resolved minimum matching should-clause count."""

    must: tuple = ()
    should: tuple = ()
    must_not: tuple = ()
    filter: tuple = ()

    def _children(self):
        return (*self.must, *self.should, *self.must_not, *self.filter)

    def can_match(self, bind, seg):
        binds = bind["children"]
        nm, ns = len(self.must), len(self.should)
        nn = len(self.must_not)
        for c, b in zip(self.must, binds[:nm]):
            if not c.can_match(b, seg):
                return False
        for c, b in zip(self.filter, binds[nm + ns + nn:]):
            if not c.can_match(b, seg):
                return False
        if ns and not self.must and not self.filter and \
                int(bind.get("required", 1)) >= 1:
            return any(c.can_match(b, seg)
                       for c, b in zip(self.should, binds[nm: nm + ns]))
        return True

    def max_score_bound(self, bind, seg):
        binds = bind["children"]
        nm, ns = len(self.must), len(self.should)
        boost = float(bind["boost"])
        if boost < 0:
            return math.inf
        total = 0.0
        for c, b in zip(self.must, binds[:nm]):
            total += c.max_score_bound(b, seg)
        for c, b in zip(self.should, binds[nm: nm + ns]):
            total += c.max_score_bound(b, seg)
        return total * boost * _BOUND_MARGIN

    def arrays(self):
        out = frozenset()
        for c in self._children():
            out |= c.arrays()
        return out

    def prepare(self, bind, seg, dseg, ctx):
        cdims, cins = _prepare_children(
            self._children(), bind["children"], seg, dseg, ctx)
        return cdims, (cins, _scalar(bind["boost"], _F32),
                       _scalar(bind["required"], _I32))

    def eval(self, A, dims, ins):
        cins, boost, required = ins
        n_pad = A["live"].shape[0]
        outs = [c.eval(A, dims[i], cins[i])
                for i, c in enumerate(self._children())]
        nm, ns, nn = len(self.must), len(self.should), len(self.must_not)
        matched = jnp.ones(n_pad, bool)
        scores = jnp.zeros(n_pad, jnp.float32)
        for s, m in outs[:nm]:                      # must
            matched &= m
            scores += s
        for _s, m in outs[nm + ns + nn:]:           # filter
            matched &= m
        for _s, m in outs[nm + ns: nm + ns + nn]:   # must_not
            matched &= ~m
        if ns:
            cnt = jnp.zeros(n_pad, jnp.int32)
            for s, m in outs[nm: nm + ns]:          # should
                cnt += m.astype(jnp.int32)
                scores += s
            matched &= cnt >= required
        scores = jnp.where(matched, scores * boost, 0.0)
        return scores, matched


@dataclass(frozen=True)
class DisMaxPlan(Plan):
    """bind: {boost, tie_breaker, children}."""

    children: tuple = ()

    def arrays(self):
        out = frozenset()
        for c in self.children:
            out |= c.arrays()
        return out

    def can_match(self, bind, seg):
        return any(c.can_match(b, seg)
                   for c, b in zip(self.children, bind["children"]))

    def max_score_bound(self, bind, seg):
        boost = float(bind["boost"])
        tie = float(bind["tie_breaker"])
        if boost < 0 or tie < 0 or tie > 1:
            return math.inf
        bounds = [c.max_score_bound(b, seg)
                  for c, b in zip(self.children, bind["children"])]
        if not bounds:
            return 0.0
        best = max(bounds)
        return (best + tie * (sum(bounds) - best)) * boost * _BOUND_MARGIN

    def prepare(self, bind, seg, dseg, ctx):
        cdims, cins = _prepare_children(
            self.children, bind["children"], seg, dseg, ctx)
        return cdims, (cins, _scalar(bind["boost"], _F32),
                       _scalar(bind["tie_breaker"], _F32))

    def eval(self, A, dims, ins):
        cins, boost, tie = ins
        n_pad = A["live"].shape[0]
        best = jnp.zeros(n_pad, jnp.float32)
        total = jnp.zeros(n_pad, jnp.float32)
        matched = jnp.zeros(n_pad, bool)
        for i, c in enumerate(self.children):
            s, m = c.eval(A, dims[i], cins[i])
            best = jnp.maximum(best, s)
            total += s
            matched |= m
        scores = best + tie * (total - best)
        return jnp.where(matched, scores * boost, 0.0), matched


@dataclass(frozen=True)
class ConstScorePlan(Plan):
    """bind: {boost, child}."""

    child: Optional[Plan] = None

    def arrays(self):
        return self.child.arrays()

    def can_match(self, bind, seg):
        return self.child.can_match(bind["child"], seg)

    max_score_bound = _boost_bound

    def prepare(self, bind, seg, dseg, ctx):
        cdims, cins = self.child.prepare(bind["child"], seg, dseg, ctx)
        return cdims, (cins, _scalar(bind["boost"], _F32))

    def eval(self, A, dims, ins):
        cins, boost = ins
        _s, matched = self.child.eval(A, dims, cins)
        return jnp.where(matched, boost, 0.0).astype(jnp.float32), matched


# ---------------------------------------------------------------------------
# Nested queries: object-space mini-plans.  A nested path's objects form
# their own padded id space; inner conditions evaluate [n_obj_pad] masks
# which scatter-OR back to parents (ToParentBlockJoinQuery's TPU shape).
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ObjTermsPlan:
    """term/terms membership over one nested child column.
    bind: {"values": [...]} (raw terms for ordinal, numbers for numeric).
    """

    field: str = ""
    kind: str = "ordinal"            # ordinal | numeric

    def prepare(self, bind, block, staged):
        col = (staged["ordinal"] if self.kind == "ordinal"
               else staged["numeric"]).get(self.field)
        if col is None:
            return None
        if self.kind == "ordinal":
            from opensearch_tpu.common.cache import attached_cache
            cache = attached_cache(block, "_term_to_ord",
                                   name="query.term_ords",
                                   max_weight=8 << 20,
                                   breaker="fielddata")
            term_to_ord = cache.get(self.field)
            if term_to_ord is None:
                ord_terms, _ords, _objs = block.ordinal[self.field]
                term_to_ord = {t: o for o, t in enumerate(ord_terms)}
                cache.put(self.field, term_to_ord)
            wanted = [term_to_ord[t] for t in bind["values"]
                      if t in term_to_ord]
            if not wanted:
                return None
            q_pad = pad_pow2(len(wanted), minimum=1)
            return (col["ords"], col["value_objs"],
                    _pad_np(wanted, q_pad, -2, _I32))
        wanted = [float(v) for v in bind["values"]]
        q_pad = pad_pow2(len(wanted), minimum=1)
        return (col["values"], col["value_objs"],
                _pad_np(wanted, q_pad, np.nan, np.float64))

    def eval(self, ins, n_obj_pad):
        if ins is None:
            return jnp.zeros(n_obj_pad, bool)
        vals, objs, wanted = ins
        hit = (vals[:, None] == wanted[None, :]).any(axis=1)
        return jnp.zeros(n_obj_pad, bool).at[objs].max(hit)


@dataclass(frozen=True)
class ObjRangePlan:
    """range over a numeric nested child.  bind: {"lo", "hi"} (floats,
    inclusivity resolved into static flags)."""

    field: str = ""
    include_lo: bool = True
    include_hi: bool = True

    def prepare(self, bind, block, staged):
        col = staged["numeric"].get(self.field)
        if col is None:
            return None
        return (col["values"], col["value_objs"],
                _scalar(bind["lo"], np.float64),
                _scalar(bind["hi"], np.float64))

    def eval(self, ins, n_obj_pad):
        if ins is None:
            return jnp.zeros(n_obj_pad, bool)
        vals, objs, lo, hi = ins
        above = vals >= lo if self.include_lo else vals > lo
        below = vals <= hi if self.include_hi else vals < hi
        return jnp.zeros(n_obj_pad, bool).at[objs].max(above & below)


@dataclass(frozen=True)
class ObjExistsPlan:
    field: str = ""

    def prepare(self, bind, block, staged):
        col = (staged["numeric"].get(self.field)
               or staged["ordinal"].get(self.field))
        if col is None:
            return None
        return (col["value_objs"],)

    def eval(self, ins, n_obj_pad):
        if ins is None:
            return jnp.zeros(n_obj_pad, bool)
        (objs,) = ins
        # padded entries point at the dead object slot
        mask = jnp.zeros(n_obj_pad, bool).at[objs].max(
            objs < n_obj_pad - 1)
        return mask


@dataclass(frozen=True)
class ObjBoolPlan:
    must: tuple = ()
    should: tuple = ()
    must_not: tuple = ()
    # shoulds required only when nothing else constrains (the top-level
    # bool's required-resolution, compiler _c_bool)
    should_required: bool = True

    def prepare(self, bind, block, staged):
        children = (*self.must, *self.should, *self.must_not)
        return tuple(c.prepare(b, block, staged)
                     for c, b in zip(children, bind["children"]))

    def eval(self, ins, n_obj_pad):
        nm, ns = len(self.must), len(self.should)
        mask = jnp.ones(n_obj_pad, bool)
        for c, i in zip(self.must, ins[:nm]):
            mask &= c.eval(i, n_obj_pad)
        if ns and self.should_required:
            any_should = jnp.zeros(n_obj_pad, bool)
            for c, i in zip(self.should, ins[nm: nm + ns]):
                any_should |= c.eval(i, n_obj_pad)
            mask &= any_should
        for c, i in zip(self.must_not, ins[nm + ns:]):
            mask &= ~c.eval(i, n_obj_pad)
        return mask


@dataclass(frozen=True)
class ObjMatchAllPlan:
    def prepare(self, bind, block, staged):
        return ()

    def eval(self, ins, n_obj_pad):
        return jnp.ones(n_obj_pad, bool)


@dataclass(frozen=True)
class NestedPlan(Plan):
    """nested query: inner object-space condition -> parent mask.
    bind: {"inner": inner_bind, "boost": f}."""

    path: str = ""
    inner: object = None             # Obj*Plan

    def prepare(self, bind, seg, dseg, ctx):
        block = seg.nested.get(self.path)
        staged = dseg.nested_staged(self.path)
        if block is None or staged is None:
            return ("missing",), ()
        inner_ins = self.inner.prepare(bind["inner"], block, staged)
        return (staged["n_obj_pad"],), (
            staged["obj_to_doc"], staged["obj_valid"], inner_ins,
            _scalar(bind["boost"], _F32))

    def eval(self, A, dims, ins):
        n_pad = A["live"].shape[0]
        if dims[0] == "missing":
            return jnp.zeros(n_pad, jnp.float32), jnp.zeros(n_pad, bool)
        n_obj_pad = dims[0]
        obj_to_doc, obj_valid, inner_ins, boost = ins
        obj_mask = self.inner.eval(inner_ins, n_obj_pad) & obj_valid
        matched = jnp.zeros(n_pad, bool).at[obj_to_doc].max(obj_mask)
        return jnp.where(matched, boost, 0.0).astype(jnp.float32), matched

    def can_match(self, bind, seg):
        return self.path in seg.nested


def _nearest_value_dist(col, origin):
    """Distance from ``origin`` to the NEAREST of a doc's values: 0 when
    origin lies inside [min, max], else the gap to the closer bound
    (multi-valued semantics of the reference's distance_feature/decay)."""
    mn = col["minv"].astype(jnp.float64)
    mx = col["maxv"].astype(jnp.float64)
    below = jnp.maximum(mn - origin, 0.0)     # origin below the range
    above = jnp.maximum(origin - mx, 0.0)     # origin above the range
    return jnp.maximum(below, above)


_EARTH_R_M = 6371008.8


def _haversine_m(lat1, lon1, lat2, lon2):
    """Vectorized great-circle distance in meters (degrees in)."""
    p1, p2 = jnp.radians(lat1), jnp.radians(lat2)
    dp = p2 - p1
    dl = jnp.radians(lon2) - jnp.radians(lon1)
    a = (jnp.sin(dp / 2) ** 2
         + jnp.cos(p1) * jnp.cos(p2) * jnp.sin(dl / 2) ** 2)
    return 2 * _EARTH_R_M * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


@dataclass(frozen=True)
class BoostingPlan(Plan):
    """boosting query: positive clause scores, docs also matching the
    negative clause get demoted by negative_boost (BoostingQueryBuilder).
    bind: {boost, negative_boost, children: (pos_bind, neg_bind)}."""

    positive: Plan = None
    negative: Plan = None

    def arrays(self):
        return self.positive.arrays() | self.negative.arrays()

    def can_match(self, bind, seg):
        return self.positive.can_match(bind["children"][0], seg)

    def max_score_bound(self, bind, seg):
        boost = float(bind["boost"])
        if boost < 0:
            return math.inf
        pos = self.positive.max_score_bound(bind["children"][0], seg)
        # negative_boost is usually in [0, 1); a larger value could
        # amplify demoted docs, so bound by whichever factor is bigger
        return (pos * boost * max(1.0, float(bind["negative_boost"]))
                * _BOUND_MARGIN)

    def prepare(self, bind, seg, dseg, ctx):
        cdims, cins = _prepare_children(
            (self.positive, self.negative), bind["children"],
            seg, dseg, ctx)
        return cdims, (cins, _scalar(bind["boost"], _F32),
                       _scalar(bind["negative_boost"], _F32))

    def eval(self, A, dims, ins):
        cins, boost, negative_boost = ins
        scores, matched = self.positive.eval(A, dims[0], cins[0])
        _ns, neg = self.negative.eval(A, dims[1], cins[1])
        scores = jnp.where(neg, scores * negative_boost, scores) * boost
        return jnp.where(matched, scores, 0.0), matched


@dataclass(frozen=True)
class TermsSetPlan(Plan):
    """terms_set: term bag whose per-doc required count comes from a
    NUMERIC FIELD of the doc itself (minimum_should_match_field;
    TermsSetQueryBuilder).  bind: {terms, idfs, weights, avgdl}."""

    field: str = ""
    msm_field: str = ""
    scored: bool = True

    def arrays(self):
        return frozenset({("postings", self.field),
                          ("numeric", self.msm_field)})

    def prepare(self, bind, seg, dseg, ctx):
        terms = bind["terms"]
        pf = seg.postings.get(self.field)
        t_pad = pad_pow2(len(terms), minimum=1)
        tids = np.zeros(t_pad, dtype=_I32)
        active = np.zeros(t_pad, dtype=bool)
        budget = 0
        for i, t in enumerate(terms):
            tid = pf.term_id(t) if pf is not None else -1
            if tid >= 0:
                tids[i] = tid
                active[i] = True
                budget += int(pf.df[tid])
        ins = (jnp.asarray(tids), jnp.asarray(active),  # staging-ok: per-query input (prep-cache owned)
               _pad_np(bind["idfs"], t_pad, 0.0, _F32),
               _pad_np(bind["weights"], t_pad, 0.0, _F32),
               dseg.impacts(self.field, bind["avgdl"]))  # quantize-ok: TermsSet stays on the f32 lowering
        return (t_pad, pad_bucket(budget)), ins

    def eval(self, A, dims, ins):
        t_pad, budget = dims
        tids, active, idfs, weights, impacts = ins
        p = A["postings"][self.field]
        msm = A["numeric"][self.msm_field]
        n_pad = A["live"].shape[0]
        scores, count = bm25_ops.impact_score_count(  # engine-ok: TermsSet lowering
            p["offsets"], p["doc_ids"], impacts, tids, active,
            idfs, weights, n_pad=n_pad, budget=budget,
            scored=self.scored)
        # per-doc minimum from the doc's own field; docs without the
        # field never match (the reference skips them)
        required = jnp.where(msm["exists"],
                             msm["minv"].astype(jnp.int64), 2**62)
        matched = (count.astype(jnp.int64) >= required) & (count > 0)
        return jnp.where(matched, scores, 0.0), matched


@dataclass(frozen=True)
class DistanceFeaturePlan(Plan):
    """distance_feature: score = boost * pivot / (pivot + distance) over
    a numeric/date or geo_point field (DistanceFeatureQueryBuilder).
    bind: {boost, pivot, origin} (origin = scalar, or (lat, lon))."""

    field: str = ""
    kind: str = "numeric"              # numeric | geo

    def arrays(self):
        group = "geo" if self.kind == "geo" else "numeric"
        return frozenset({(group, self.field)})

    def prepare(self, bind, seg, dseg, ctx):
        if self.kind == "geo":
            lat, lon = bind["origin"]
            origin = (jnp.asarray(np.float64(lat)),  # staging-ok: per-query input (prep-cache owned)
                      jnp.asarray(np.float64(lon)))  # staging-ok: per-query input (prep-cache owned)
        else:
            origin = _scalar(bind["origin"], np.float64)
        return (), (origin, _scalar(bind["pivot"], np.float64),
                    _scalar(bind["boost"], _F32))

    def eval(self, A, dims, ins):
        origin, pivot, boost = ins
        n_pad = A["live"].shape[0]
        if self.kind == "geo":
            g = A["geo"][self.field]
            lat0, lon0 = origin
            d_entry = _haversine_m(g["lats"].astype(jnp.float64),
                                   g["lons"].astype(jnp.float64),
                                   lat0, lon0)
            dist = jnp.full(n_pad, jnp.inf).at[g["value_docs"]].min(d_entry)
            exists = g["exists"]
        else:
            col = A["numeric"][self.field]
            dist = _nearest_value_dist(col, origin)
            exists = col["exists"]
        score = boost * (pivot / (pivot + dist))
        matched = exists
        return jnp.where(matched, score, 0.0).astype(jnp.float32), matched


@dataclass(frozen=True)
class GeoDistancePlan(Plan):
    """geo_distance filter: any of the doc's points within ``distance``
    meters of the origin.  bind: {lat, lon, distance_m, boost}."""

    field: str = ""

    def arrays(self):
        return frozenset({("geo", self.field)})

    def prepare(self, bind, seg, dseg, ctx):
        return (), (jnp.asarray(np.float64(bind["lat"])),  # staging-ok: per-query input (prep-cache owned)
                    jnp.asarray(np.float64(bind["lon"])),  # staging-ok: per-query input (prep-cache owned)
                    jnp.asarray(np.float64(bind["distance_m"])),  # staging-ok: per-query input (prep-cache owned)
                    _scalar(bind["boost"], _F32))

    def eval(self, A, dims, ins):
        lat0, lon0, dist_m, boost = ins
        g = A["geo"][self.field]
        n_pad = A["live"].shape[0]
        d_entry = _haversine_m(g["lats"].astype(jnp.float64),
                               g["lons"].astype(jnp.float64), lat0, lon0)
        hit = jnp.zeros(n_pad, bool).at[g["value_docs"]].max(
            d_entry <= dist_m)
        matched = hit & g["exists"]
        return jnp.where(matched, boost, 0.0).astype(jnp.float32), matched


@dataclass(frozen=True)
class GeoPolygonPlan(Plan):
    """geo_polygon filter: even-odd ray casting over the polygon's edge
    list, vectorized values x edges (GeoPolygonQueryBuilder; planar
    approximation like the reference's legacy path).  bind: {lats, lons
    (padded to v_pad, inactive edges zero-length), boost}."""

    field: str = ""

    def arrays(self):
        return frozenset({("geo", self.field)})

    def prepare(self, bind, seg, dseg, ctx):
        lats = np.asarray(bind["lats"], np.float64)
        lons = np.asarray(bind["lons"], np.float64)
        v_pad = pad_pow2(len(lats), minimum=4)
        # pad by repeating the last vertex: zero-length edges never cross
        plats = np.full(v_pad, lats[-1])
        plons = np.full(v_pad, lons[-1])
        plats[: len(lats)] = lats
        plons[: len(lons)] = lons
        return ((v_pad,), (jnp.asarray(plats), jnp.asarray(plons),  # staging-ok: per-query input (prep-cache owned)
                           _scalar(bind["boost"], _F32)))

    def eval(self, A, dims, ins):
        plats, plons, boost = ins
        g = A["geo"][self.field]
        n_pad = A["live"].shape[0]
        y = g["lats"].astype(jnp.float64)[:, None]      # [V, 1]
        x = g["lons"].astype(jnp.float64)[:, None]
        yi, xi = plats[None, :], plons[None, :]         # [1, E]
        yj = jnp.roll(plats, -1)[None, :]
        xj = jnp.roll(plons, -1)[None, :]
        straddles = (yi > y) != (yj > y)
        # safe where straddles is False (the denominator can be 0 there)
        t = jnp.where(straddles, (y - yi) / jnp.where(yj - yi == 0, 1.0,
                                                      yj - yi), 0.0)
        crosses = straddles & (x < xi + t * (xj - xi))
        inside = (crosses.sum(axis=1) % 2) == 1
        hit = jnp.zeros(n_pad, bool).at[g["value_docs"]].max(inside)
        matched = hit & g["exists"]
        return jnp.where(matched, boost, 0.0).astype(jnp.float32), matched


@dataclass(frozen=True)
class GeoBoxPlan(Plan):
    """geo_bounding_box filter.  bind: {top, left, bottom, right, boost}
    (no dateline wrap)."""

    field: str = ""

    def arrays(self):
        return frozenset({("geo", self.field)})

    def prepare(self, bind, seg, dseg, ctx):
        return (), tuple(jnp.asarray(np.float64(bind[k]))  # staging-ok: per-query input (prep-cache owned)
                         for k in ("top", "left", "bottom", "right")) + (
            _scalar(bind["boost"], _F32),)

    def eval(self, A, dims, ins):
        top, left, bottom, right, boost = ins
        g = A["geo"][self.field]
        n_pad = A["live"].shape[0]
        lats = g["lats"].astype(jnp.float64)
        lons = g["lons"].astype(jnp.float64)
        inside = ((lats <= top) & (lats >= bottom)
                  & (lons >= left) & (lons <= right))
        hit = jnp.zeros(n_pad, bool).at[g["value_docs"]].max(inside)
        matched = hit & g["exists"]
        return jnp.where(matched, boost, 0.0).astype(jnp.float32), matched


@dataclass(frozen=True)
class FunctionSpec:
    """One function_score function — static structure only; its dynamic
    params ride the bind tree."""

    kind: str = "weight"      # weight|field_value_factor|random_score|
    #                           script_score|decay
    filter: Optional[Plan] = None
    field: str = ""           # fvf / decay target
    modifier: str = "none"    # fvf modifier
    decay_fn: str = "gauss"   # gauss|exp|linear
    geo: bool = False         # decay over a geo field
    program: object = None    # scripting.ScriptProgram for script_score


@dataclass(frozen=True)
class FunctionScorePlan(Plan):
    """function_score (FunctionScoreQueryBuilder + functionscore/ dir):
    child score combined with per-doc function factors.
    bind: {boost, child, functions: tuple of per-function binds
    ({filter, weight, ...params}), max_boost, min_score}."""

    child: Plan = None
    functions: tuple = ()              # tuple[FunctionSpec]
    score_mode: str = "multiply"       # multiply|sum|avg|first|max|min
    boost_mode: str = "multiply"       # multiply|replace|sum|avg|max|min

    def arrays(self):
        out = self.child.arrays()
        for f in self.functions:
            if f.filter is not None:
                out |= f.filter.arrays()
            if f.kind in ("field_value_factor", "decay") and not f.geo:
                out |= frozenset({("numeric", f.field)})
            if f.kind == "decay" and f.geo:
                out |= frozenset({("geo", f.field)})
            if f.kind == "script_score" and f.program is not None:
                for nf in f.program.numeric_fields:
                    out |= frozenset({("numeric", nf)})
                for vf in f.program.vector_fields:
                    out |= frozenset({("vector", vf)})
        return out

    # fixed positional param layout per function kind (ins pytrees carry
    # no strings — jit inputs must be arrays)
    _PARAM_ORDER = {
        "weight": ("weight",),
        "field_value_factor": ("factor", "missing", "weight"),
        "random_score": ("seed", "salt", "weight"),
        "script_score": ("weight",),
        "decay": ("origin", "scale", "offset", "decay", "weight"),
        "decay_geo": ("origin_lat", "origin_lon", "scale", "offset",
                      "decay", "weight"),
    }
    _PARAM_DEFAULTS = {"weight": 1.0, "factor": 1.0, "missing": 1.0,
                       "seed": 0.0, "salt": 0.0, "offset": 0.0,
                       "decay": 0.5}

    def _param_names(self, spec):
        key = ("decay_geo" if spec.kind == "decay" and spec.geo
               else spec.kind)
        return self._PARAM_ORDER[key]

    def prepare(self, bind, seg, dseg, ctx):
        cdims, cins = self.child.prepare(bind["child"], seg, dseg, ctx)
        fdims, fins = [], []
        for spec, fb in zip(self.functions, bind["functions"]):
            d_i, i_i = (), []
            if spec.filter is not None:
                fd, fi = spec.filter.prepare(fb["filter"], seg, dseg, ctx)
                d_i = fd
                i_i.append(fi)
            if spec.kind == "script_score":
                i_i.append(spec.program.param_values())
            fb = dict(fb)
            if spec.kind == "random_score":
                # per-segment salt so random_score differs across segments
                import zlib
                fb["salt"] = float(zlib.crc32(seg.seg_id.encode()))
            params = tuple(
                jnp.asarray(np.float64(  # staging-ok: per-query input (prep-cache owned)
                    fb.get(name, self._PARAM_DEFAULTS.get(name, 0.0))))
                for name in self._param_names(spec))
            i_i.append(params)
            fdims.append(d_i)
            fins.append(tuple(i_i))
        return (cdims, tuple(fdims)), (
            cins, tuple(fins), _scalar(bind["boost"], _F32),
            _scalar(bind.get("max_boost")
                    if bind.get("max_boost") is not None else np.inf,
                    np.float64),
            _scalar(bind.get("min_score")
                    if bind.get("min_score") is not None else -np.inf,
                    _F32))

    def _factor(self, spec, A, fdim, fin, n_pad, child_scores):
        parts = list(fin)
        params = dict(zip(self._param_names(spec), parts[-1]))
        value = None
        if spec.kind == "weight":
            value = jnp.full(n_pad, params["weight"])
        elif spec.kind == "field_value_factor":
            col = A["numeric"][spec.field]
            v = jnp.where(col["exists"],
                          col["minv"].astype(jnp.float64),
                          params.get("missing", 1.0))
            v = v * params.get("factor", 1.0)
            mod = spec.modifier
            if mod == "log":
                v = jnp.log10(jnp.maximum(v, 1e-12))
            elif mod == "log1p":
                v = jnp.log10(1.0 + jnp.maximum(v, 0.0))
            elif mod == "log2p":
                v = jnp.log10(2.0 + jnp.maximum(v, 0.0))
            elif mod == "ln":
                v = jnp.log(jnp.maximum(v, 1e-12))
            elif mod == "ln1p":
                v = jnp.log1p(jnp.maximum(v, 0.0))
            elif mod == "ln2p":
                v = jnp.log(2.0 + jnp.maximum(v, 0.0))
            elif mod == "sqrt":
                v = jnp.sqrt(jnp.maximum(v, 0.0))
            elif mod == "square":
                v = v * v
            elif mod == "reciprocal":
                v = 1.0 / jnp.where(v == 0, 1e-12, v)
            value = v * params.get("weight", 1.0)
        elif spec.kind == "random_score":
            seed = (params["seed"] + params["salt"]).astype(jnp.uint32)
            idx = jnp.arange(n_pad, dtype=jnp.uint32)
            x = idx * jnp.uint32(2654435761) + seed
            x = (x ^ (x >> 16)) * jnp.uint32(0x45D9F3B)
            x = (x ^ (x >> 16)) * jnp.uint32(0x45D9F3B)
            x = x ^ (x >> 16)
            value = (x.astype(jnp.float64) / jnp.float64(2**32)) \
                * params["weight"]
        elif spec.kind == "script_score":
            script_params = parts[-2]
            ncols = {f: (jnp.where(A["numeric"][f]["exists"],
                                   A["numeric"][f]["minv"]
                                   .astype(jnp.float32), 0.0),
                         A["numeric"][f]["exists"])
                     for f in spec.program.numeric_fields}
            vcols = {f: (A["vector"][f]["values"], A["vector"][f]["exists"])
                     for f in spec.program.vector_fields}
            value = spec.program.eval(child_scores, ncols, vcols,
                                      script_params) \
                * params.get("weight", 1.0)
            value = jnp.broadcast_to(value, (n_pad,))
        elif spec.kind == "decay":
            if spec.geo:
                g = A["geo"][spec.field]
                d_entry = _haversine_m(
                    g["lats"].astype(jnp.float64),
                    g["lons"].astype(jnp.float64),
                    params["origin_lat"], params["origin_lon"])
                dist = jnp.full(n_pad, jnp.inf).at[
                    g["value_docs"]].min(d_entry)
                dist = jnp.where(g["exists"], dist, 0.0)
            else:
                col = A["numeric"][spec.field]
                dist = jnp.where(
                    col["exists"],
                    _nearest_value_dist(col, params["origin"]), 0.0)
            eff = jnp.maximum(dist - params.get("offset", 0.0), 0.0)
            scale = params["scale"]
            decay = params.get("decay", 0.5)
            if spec.decay_fn == "gauss":
                sigma2 = -(scale ** 2) / (2.0 * jnp.log(decay))
                value = jnp.exp(-(eff ** 2) / (2.0 * sigma2))
            elif spec.decay_fn == "exp":
                lam = jnp.log(decay) / scale
                value = jnp.exp(lam * eff)
            else:                      # linear
                s = scale / (1.0 - decay)
                value = jnp.maximum((s - eff) / s, 0.0)
            value = value * params.get("weight", 1.0)
        applicable = jnp.ones(n_pad, bool)
        if spec.filter is not None:
            _fs, fmask = spec.filter.eval(A, fdim, parts[0])
            applicable = fmask
        return value.astype(jnp.float64), applicable

    def eval(self, A, dims, ins):
        cdims, fdims = dims
        cins, fins, boost, max_boost, min_score = ins
        scores, matched = self.child.eval(A, cdims, cins)
        n_pad = A["live"].shape[0]
        s64 = scores.astype(jnp.float64)
        if self.functions:
            values, apps = [], []
            for spec, fd, fi in zip(self.functions, fdims, fins):
                v, app = self._factor(spec, A, fd, fi, n_pad, scores)
                values.append(v)
                apps.append(app)
            any_app = apps[0]
            for a in apps[1:]:
                any_app = any_app | a
            if self.score_mode == "multiply":
                factor = jnp.ones(n_pad, jnp.float64)
                for v, a in zip(values, apps):
                    factor = factor * jnp.where(a, v, 1.0)
            elif self.score_mode == "sum":
                factor = jnp.zeros(n_pad, jnp.float64)
                for v, a in zip(values, apps):
                    factor = factor + jnp.where(a, v, 0.0)
            elif self.score_mode == "avg":
                # WEIGHTED average (values already carry their weight;
                # divide by the applicable weights, not the count)
                tot = jnp.zeros(n_pad, jnp.float64)
                wsum = jnp.zeros(n_pad, jnp.float64)
                for v, a, fi in zip(values, apps, fins):
                    w = fi[-1][-1]          # params tuple ends in weight
                    tot = tot + jnp.where(a, v, 0.0)
                    wsum = wsum + jnp.where(a, w, 0.0)
                factor = tot / jnp.maximum(wsum, 1e-12)
            elif self.score_mode == "max":
                factor = jnp.full(n_pad, -jnp.inf)
                for v, a in zip(values, apps):
                    factor = jnp.maximum(factor,
                                         jnp.where(a, v, -jnp.inf))
            elif self.score_mode == "min":
                factor = jnp.full(n_pad, jnp.inf)
                for v, a in zip(values, apps):
                    factor = jnp.minimum(factor, jnp.where(a, v, jnp.inf))
            else:                      # first
                factor = jnp.zeros(n_pad, jnp.float64)
                assigned = jnp.zeros(n_pad, bool)
                for v, a in zip(values, apps):
                    take = a & ~assigned
                    factor = jnp.where(take, v, factor)
                    assigned = assigned | a
            factor = jnp.where(any_app, factor, 1.0)
        else:
            factor = jnp.ones(n_pad, jnp.float64)
        factor = jnp.minimum(factor, max_boost)
        if self.boost_mode == "multiply":
            out = s64 * factor
        elif self.boost_mode == "replace":
            out = factor
        elif self.boost_mode == "sum":
            out = s64 + factor
        elif self.boost_mode == "avg":
            out = (s64 + factor) / 2.0
        elif self.boost_mode == "max":
            out = jnp.maximum(s64, factor)
        else:                          # min
            out = jnp.minimum(s64, factor)
        out = (out * boost).astype(jnp.float32)
        matched = matched & (out >= min_score)
        return jnp.where(matched, out, 0.0), matched


# constant-score leaves: the boost is the only score either of these
# families can produce, so it IS the block-max bound
for _cls in (NumericTermsPlan, NumericRangePlan, OrdinalRangePlan,
             PostingsMaskPlan, TermRangeMaskPlan, ExpandTermsPlan,
             ExistsPlan, MaskPlan, NestedPlan, GeoDistancePlan,
             GeoPolygonPlan, GeoBoxPlan):
    _cls.max_score_bound = _boost_bound
del _cls


def _edit_distance_le(a: str, b: str, k: int) -> bool:
    """Banded optimal-string-alignment distance (Levenshtein WITH
    transpositions — Lucene's fuzzy default, fuzzy_transpositions=true):
    True iff distance(a, b) <= k."""
    if abs(len(a) - len(b)) > k:
        return False
    if k == 0:
        return a == b
    prev2 = None
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        lo = max(1, i - k)
        hi = min(len(b), i + k)
        if lo > 1:
            cur[lo - 1] = k + 1
        for j in range(lo, hi + 1):
            cost = 0 if ca == b[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if (prev2 is not None and i > 1 and j > 1
                    and ca == b[j - 2] and a[i - 2] == b[j - 1]):
                cur[j] = min(cur[j], prev2[j - 2] + 1)   # transposition
        for j in range(hi + 1, len(b) + 1):
            cur[j] = k + 1
        prev2, prev = prev, cur
        if min(prev) > k:
            return False
    return prev[len(b)] <= k


# ---------------------------------------------------------------------------
# jit entry points.  plan/dims/k are static; A/ins are traced.
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnums=(0, 1, 2))
def run_topk(plan: Plan, dims, k: int, A, ins, min_score):
    """(top_scores[k], top_local_ids[k], total_matched, max_score).
    top_k's lower-index tie-break == Lucene's ascending-doc-id tie-break.
    ``min_score`` (-inf when unset) excludes docs from hits AND total,
    matching MinimumScoreCollector semantics."""
    scores, matched = plan.eval(A, dims, ins)
    matched = matched & A["live"] & (scores >= min_score)
    key = jnp.where(matched, scores, -jnp.inf)
    vals, idx = lax.top_k(key, k)
    return vals, idx, matched.sum(), jnp.max(key)


@partial(jax.jit, static_argnums=(1,))
def topk_from_scores(scores, k: int, matched):
    """Top-k over an already-computed (scores, matched) pair — used when a
    full-scores pass already ran for aggregations."""
    key = jnp.where(matched, scores, -jnp.inf)
    vals, idx = lax.top_k(key, k)
    return vals, idx, matched.sum(), jnp.max(key)


@partial(jax.jit, static_argnums=(0, 1))
def run_full(plan: Plan, dims, A, ins, min_score):
    """(scores[n_pad] zeroed-unmatched, matched[n_pad]) — for aggs, sorts,
    counts."""
    scores, matched = plan.eval(A, dims, ins)
    matched = matched & A["live"] & (scores >= min_score)
    return jnp.where(matched, scores, 0.0), matched
