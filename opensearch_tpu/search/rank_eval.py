"""Rank evaluation: IR quality metrics over judged queries.

Analog of ``modules/rank-eval`` (3.9k LoC): precision@k, recall@k,
mean reciprocal rank, (n)DCG, expected reciprocal rank over a set of
rated search requests — SURVEY flags this module as the recall@10
verification harness for the BASELINE configs.
"""

from __future__ import annotations

import math

from opensearch_tpu.common.errors import ParsingError


def _rating_of(ratings: dict, index: str, doc_id: str) -> int:
    return ratings.get((index, doc_id), 0)


def _metric_precision(hits, ratings, k: int, threshold: int) -> float:
    top = hits[:k]
    if not top:
        return 0.0
    rel = sum(1 for h in top
              if _rating_of(ratings, h["_index"], h["_id"]) >= threshold)
    return rel / len(top)


def _metric_recall(hits, ratings, k: int, threshold: int) -> float:
    total_rel = sum(1 for r in ratings.values() if r >= threshold)
    if total_rel == 0:
        return 0.0
    top = hits[:k]
    rel = sum(1 for h in top
              if _rating_of(ratings, h["_index"], h["_id"]) >= threshold)
    return rel / total_rel


def _metric_mrr(hits, ratings, k: int, threshold: int) -> float:
    for rank, h in enumerate(hits[:k], 1):
        if _rating_of(ratings, h["_index"], h["_id"]) >= threshold:
            return 1.0 / rank
    return 0.0


def _dcg(gains: list[float]) -> float:
    return sum(g / math.log2(i + 2) for i, g in enumerate(gains))


def _make_dcg(normalize: bool):
    def metric(hits, ratings, k: int, _threshold: int) -> float:
        gains = [(2 ** _rating_of(ratings, h["_index"], h["_id"])) - 1
                 for h in hits[:k]]
        if not normalize:
            return _dcg(gains)       # raw DCG (the reference's default)
        ideal = sorted(((2 ** r) - 1 for r in ratings.values()),
                       reverse=True)[:k]
        idcg = _dcg(ideal)
        return _dcg(gains) / idcg if idcg > 0 else 0.0
    return metric


def _metric_err(hits, ratings, k: int, _threshold: int) -> float:
    max_r = max((r for r in ratings.values()), default=0)
    if max_r == 0:
        return 0.0
    err = 0.0
    p_stop = 1.0
    for rank, h in enumerate(hits[:k], 1):
        r = _rating_of(ratings, h["_index"], h["_id"])
        util = ((2 ** r) - 1) / (2 ** max_r)
        err += p_stop * util / rank
        p_stop *= (1 - util)
    return err


_METRICS = {
    "precision": (_metric_precision, "precision_at_k"),
    "recall": (_metric_recall, "recall_at_k"),
    "mean_reciprocal_rank": (_metric_mrr, "mrr"),
    "dcg": (None, "dcg"),        # built per request (normalize option)
    "expected_reciprocal_rank": (_metric_err, "err"),
}


def run_rank_eval(body: dict, search_fn) -> dict:
    """``search_fn(index_expr, search_body) -> search response``.

    Body shape mirrors the reference's _rank_eval API: ``requests`` each
    with id/request/ratings, one ``metric`` object.
    """
    requests = body.get("requests")
    if not requests:
        raise ParsingError("[rank_eval] requires [requests]")
    metric_obj = body.get("metric")
    if not isinstance(metric_obj, dict) or len(metric_obj) != 1:
        raise ParsingError("[rank_eval] requires exactly one [metric]")
    ((metric_name, mconf),) = metric_obj.items()
    if metric_name not in _METRICS:
        raise ParsingError(
            f"unknown rank_eval metric [{metric_name}] — supported: "
            f"{sorted(_METRICS)}")
    mconf = mconf or {}
    k = int(mconf.get("k", 10))
    threshold = int(mconf.get("relevant_rating_threshold", 1))
    if metric_name == "dcg":
        fn = _make_dcg(bool(mconf.get("normalize", False)))
    else:
        fn, _label = _METRICS[metric_name]

    details = {}
    failures = {}
    scores = []
    for r in requests:
        rid = r.get("id")
        if not rid:
            raise ParsingError("each rank_eval request needs an [id]")
        ratings = {}
        for rating in r.get("ratings") or []:
            ratings[(rating["_index"], str(rating["_id"]))] = \
                int(rating["rating"])
        index_expr = ",".join(r.get("index") or ["_all"]) \
            if isinstance(r.get("index"), list) else (r.get("index")
                                                      or "_all")
        search_body = dict(r.get("request") or {})
        # FORCE the window: an explicit smaller size would silently
        # deflate every metric (the reference's forcedSearchSize)
        search_body["size"] = max(k, int(search_body.get("size", 0)))
        try:
            resp = search_fn(index_expr, search_body)
        except Exception as e:       # noqa: BLE001 — per-request failure
            failures[rid] = {"type": type(e).__name__, "reason": str(e)}
            continue
        hits = resp["hits"]["hits"]
        score = fn(hits, ratings, k, threshold)
        scores.append(score)
        details[rid] = {
            "metric_score": round(score, 6),
            "unrated_docs": [
                {"_index": h["_index"], "_id": h["_id"]}
                for h in hits[:k]
                if (h["_index"], h["_id"]) not in ratings],
            "hits": [{"hit": {"_index": h["_index"], "_id": h["_id"],
                              "_score": h.get("_score")},
                      "rating": ratings.get((h["_index"], h["_id"]))}
                     for h in hits[:k]],
        }
    quality = sum(scores) / len(scores) if scores else 0.0
    return {"metric_score": round(quality, 6), "details": details,
            "failures": failures}
