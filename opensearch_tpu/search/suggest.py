"""Suggesters: term (did-you-mean per token) and phrase (whole-input
correction).

Analog of ``search/suggest/`` (term, phrase suggesters; the completion
suggester's FST is replaced by the same vocabulary scan).  Candidate
generation walks the shard vocabulary with a banded edit-distance
check — a host-side operation over the term dictionary, exactly where
the reference runs its DirectSpellChecker.
"""

from __future__ import annotations

from typing import Optional

from opensearch_tpu.common.errors import (IllegalArgumentError,
                                          ParsingError)


def _edit_distance(a: str, b: str, cap: int) -> int:
    """Banded Levenshtein, capped at ``cap`` + 1."""
    if abs(len(a) - len(b)) > cap:
        return cap + 1
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        lo, hi = max(1, i - cap), min(len(b), i + cap)
        if lo > 1:
            cur[lo - 1] = cap + 1
        for j in range(lo, hi + 1):
            cost = 0 if ca == b[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
        for j in range(hi + 1, len(b) + 1):
            cur[j] = cap + 1
        prev = cur
        if min(prev) > cap:
            return cap + 1
    return prev[-1]


class Suggester:
    def __init__(self, ctx):
        self.ctx = ctx               # compiler.ShardContext

    # -- vocabulary access -------------------------------------------------

    def _vocab(self, field: str) -> dict[str, int]:
        """term -> df across the context's segments (cached on the
        searcher context: segments are immutable, so one scan serves
        every suggester until the searcher is reopened)."""
        from opensearch_tpu.common.cache import attached_cache
        cache = attached_cache(self.ctx, "_suggest_vocab",
                               name="suggest.vocab",
                               max_weight=32 << 20, breaker="fielddata")
        vocab = cache.get(field)
        if vocab is not None:
            return vocab
        out: dict[str, int] = {}
        for seg in self.ctx.segments:
            pf = seg.postings.get(field)
            if pf is None:
                continue
            for term, tid in pf.terms.items():
                df = int(pf.df[tid])
                if df > 0:
                    out[term] = out.get(term, 0) + df
        cache.put(field, out)
        return out

    def _candidates(self, term: str, vocab: dict, max_edits: int,
                    prefix_length: int, min_len: int = 1) -> list:
        """[(candidate, df, distance)] sorted by (distance, -df)."""
        prefix = term[:prefix_length]
        out = []
        for cand, df in vocab.items():
            if len(cand) < min_len:
                continue
            if prefix_length and not cand.startswith(prefix):
                continue
            d = _edit_distance(term, cand, max_edits)
            if d <= max_edits:
                out.append((cand, df, d))
        out.sort(key=lambda t: (t[2], -t[1], t[0]))
        return out

    # -- term suggester ----------------------------------------------------

    def term_suggest(self, text: str, spec: dict) -> list[dict]:
        field = spec.get("field")
        if not field:
            raise ParsingError("[term] suggester requires a [field]")
        ft = self.ctx.field_type(field)
        if ft is None or not hasattr(ft, "search_terms"):
            raise IllegalArgumentError(
                f"[term] suggester field [{field}] must be a text field")
        max_edits = int(spec.get("max_edits", 2))
        if not (1 <= max_edits <= 2):
            raise IllegalArgumentError("[max_edits] must be 1 or 2")
        size = int(spec.get("size", 5))
        prefix_length = int(spec.get("prefix_length", 1))
        suggest_mode = spec.get("suggest_mode", "missing")
        vocab = self._vocab(field)
        out = []
        import re as _re
        for m in _re.finditer(r"\S+", str(text)):
            token = m.group()
            terms = ft.search_terms(token, self.ctx.mapper.analyzers)
            analyzed = terms[0] if terms else token.lower()
            entry = {"text": token, "offset": m.start(),
                     "length": len(token), "options": []}
            in_vocab = analyzed in vocab
            if not (suggest_mode == "missing" and in_vocab):
                for cand, df, dist in self._candidates(
                        analyzed, vocab, max_edits, prefix_length):
                    if cand == analyzed:
                        continue
                    if suggest_mode == "popular" and in_vocab and \
                            df <= vocab[analyzed]:
                        continue
                    entry["options"].append({
                        "text": cand, "freq": df,
                        "score": round(
                            1.0 - dist / max(len(analyzed), 1), 5)})
                    if len(entry["options"]) >= size:
                        break
            out.append(entry)
        return out

    # -- phrase suggester --------------------------------------------------

    def phrase_suggest(self, text: str, spec: dict) -> list[dict]:
        """Whole-input correction: per-token best candidate joined back
        (the reference's phrase suggester scores candidate lattices with
        a language model; the unigram-df greedy walk is its degenerate
        laplace-smoothed case)."""
        field = spec.get("field")
        if not field:
            raise ParsingError("[phrase] suggester requires a [field]")
        ft = self.ctx.field_type(field)
        if ft is None or not hasattr(ft, "search_terms"):
            raise IllegalArgumentError(
                f"[phrase] suggester field [{field}] must be text")
        max_errors = float(spec.get("max_errors", 1.0))
        size = int(spec.get("size", 1))
        vocab = self._vocab(field)
        tokens = str(text).split()
        budget = (int(max_errors) if max_errors >= 1
                  else max(1, int(max_errors * len(tokens))))
        corrected = []
        changed = 0
        for token in tokens:
            terms = ft.search_terms(token, self.ctx.mapper.analyzers)
            analyzed = terms[0] if terms else token.lower()
            if analyzed in vocab or changed >= budget:
                corrected.append((token, False))
                continue
            cands = self._candidates(analyzed, vocab, 2, 1)
            if cands:
                corrected.append((cands[0][0], True))
                changed += 1
            else:
                corrected.append((token, False))
        options = []
        if changed:
            phrase = " ".join(t for t, _c in corrected)
            highlighted = None
            if spec.get("highlight"):
                pre = spec["highlight"].get("pre_tag", "<em>")
                post = spec["highlight"].get("post_tag", "</em>")
                highlighted = " ".join(
                    f"{pre}{t}{post}" if c else t for t, c in corrected)
            opt = {"text": phrase,
                   "score": round(1.0 / (1.0 + changed), 5)}
            if highlighted is not None:
                opt["highlighted"] = highlighted
            options.append(opt)
        return [{"text": text, "offset": 0, "length": len(text),
                 "options": options[:size]}]


def completion_suggest(ctx, prefix: str, spec: dict) -> list[dict]:
    """Prefix completion over the sorted ordinal column — a
    binary-searched range per segment instead of an FST walk
    (suggest/completion/CompletionSuggester.java), merged by best
    weight across segments."""
    import bisect

    field = spec.get("field")
    if not field:
        raise ParsingError("[completion] requires a [field]")
    size = int(spec.get("size", 5))
    skip_dup = bool(spec.get("skip_duplicates", False))
    best: dict[str, tuple] = {}      # input -> (weight, doc_id, seg, d)
    for seg in ctx.segments:
        dv = seg.ordinal_dv.get(field)
        if dv is None or not dv.ord_terms:
            continue
        # ord -> docs, built once per (immutable) segment+field
        from opensearch_tpu.common.cache import attached_cache
        cache = attached_cache(seg, "_completion_cache",
                               name="suggest.completion",
                               max_weight=16 << 20, breaker="fielddata")
        docs_of = cache.get(field)
        if docs_of is None:
            docs_of = {}
            for d, o in zip(dv.value_docs, dv.ords):
                if o >= 0:
                    docs_of.setdefault(int(o), []).append(int(d))
            cache.put(field, docs_of)
        weights = seg.completion_weights.get(field, {})
        lo = bisect.bisect_left(dv.ord_terms, prefix)
        for o in range(lo, len(dv.ord_terms)):
            text = dv.ord_terms[o]
            if not text.startswith(prefix):
                break
            for d in docs_of.get(o, ()):
                if not seg.live[d]:
                    continue
                w = weights.get((d, text), 1)
                cur = best.get(text)
                if cur is None or w > cur[0]:
                    best[text] = (w, seg.doc_ids[d], seg, d)
    ranked = sorted(best.items(), key=lambda kv: (-kv[1][0], kv[0]))
    seen_docs: set = set()
    options = []
    for text, (w, doc_id, seg, d) in ranked:
        if skip_dup and doc_id in seen_docs:
            continue
        seen_docs.add(doc_id)
        opt = {"text": text, "_id": doc_id, "_score": float(w)}
        src_doc = seg.source(d)
        if src_doc is not None:
            opt["_source"] = src_doc
        options.append(opt)
        if len(options) >= size:
            break
    return [{"text": prefix, "offset": 0, "length": len(prefix),
             "options": options}]


def run_suggest(suggest_json: dict, ctx) -> dict:
    """The search body's ``suggest`` section -> response ``suggest``
    object (SearchService's suggest phase)."""
    s = Suggester(ctx)
    out = {}
    global_text = suggest_json.get("text")
    for name, body in suggest_json.items():
        if name == "text":
            continue
        if not isinstance(body, dict):
            raise ParsingError(f"suggester [{name}] must be an object")
        if "completion" in body:
            prefix = body.get("prefix", body.get("text", global_text))
            if prefix is None:
                raise ParsingError(
                    f"suggester [{name}] requires [prefix]")
            out[name] = completion_suggest(ctx, str(prefix),
                                           body["completion"])
            continue
        text = body.get("text", global_text)
        if text is None:
            raise ParsingError(f"suggester [{name}] requires [text]")
        if "term" in body:
            out[name] = s.term_suggest(text, body["term"])
        elif "phrase" in body:
            out[name] = s.phrase_suggest(text, body["phrase"])
        else:
            raise ParsingError(
                f"suggester [{name}] must be [term], [phrase] or "
                "[completion]")
    return out


def merge_suggest(per_source: list[dict]) -> dict:
    """Coordinator reduce of per-source suggest sections: options merge
    by text (freqs sum, best score wins), re-sorted (the reference's
    Suggest.reduce)."""
    out: dict = {}
    for section in per_source:
        if not section:
            continue
        for name, entries in section.items():
            if name not in out:
                out[name] = [dict(e, options=list(e["options"]))
                             for e in entries]
                continue
            for mine, theirs in zip(out[name], entries):
                by_text = {o["text"]: dict(o) for o in mine["options"]}
                for o in theirs["options"]:
                    cur = by_text.get(o["text"])
                    if cur is None:
                        by_text[o["text"]] = dict(o)
                    else:
                        cur["freq"] = cur.get("freq", 0) + o.get("freq", 0)
                        for sk in ("score", "_score"):
                            if sk in cur or sk in o:
                                cur[sk] = max(cur.get(sk, 0),
                                              o.get(sk, 0))
                # completion options carry "_score" (weights), term/
                # phrase carry "score" — both sort weight/score desc
                merged = sorted(
                    by_text.values(),
                    key=lambda o: (-o.get("score",
                                          o.get("_score", 0)),
                                   -o.get("freq", 0), o["text"]))
                mine["options"] = merged
    return out
