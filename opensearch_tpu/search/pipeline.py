"""Search pipelines: request/response processors around search, and the
hybrid-query score-normalization processor (BASELINE config #4).

Analog of the reference's SearchPipelineService (ref
search/pipeline/SearchPipelineService.java:1, Pipeline.java) plus the
out-of-tree neural-search plugin's normalization processor — the hook
named in SURVEY §2.1 as "the hook the neural-search hybrid normalization
processor uses".  A pipeline is a named JSON document; the one
phase-results processor implemented is ``normalization-processor``:

- normalization: ``min_max`` (per sub-query: (s-min)/(max-min), 1.0 on
  a degenerate range) or ``l2`` (s / ||scores||);
- combination: ``arithmetic_mean`` / ``geometric_mean`` /
  ``harmonic_mean`` with optional per-sub-query ``weights``.

A ``hybrid`` query's sub-queries each produce an independent top-k on
device; normalization+combination is a tiny host reduce over those
lists (the coordinator-side phase in the reference), so nothing here
touches the device path.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

import numpy as np

from opensearch_tpu.common.errors import (IllegalArgumentError,
                                          OpenSearchTpuError,
                                          ValidationError)


class PipelineMissingError(OpenSearchTpuError):
    status = 404


DEFAULT_NORMALIZATION = {"technique": "min_max"}
DEFAULT_COMBINATION = {"technique": "arithmetic_mean"}


def normalize_scores(scores: np.ndarray, technique: str) -> np.ndarray:
    if len(scores) == 0:
        return scores
    if technique == "min_max":
        lo, hi = float(scores.min()), float(scores.max())
        if hi - lo < 1e-12:
            return np.ones_like(scores)
        return (scores - lo) / (hi - lo)
    if technique == "l2":
        norm = float(np.sqrt((scores * scores).sum()))
        return scores / norm if norm > 1e-12 else np.ones_like(scores)
    raise IllegalArgumentError(
        f"unknown normalization technique [{technique}]")


def combine_scores(per_query: list[float], weights: list[float],
                   technique: str) -> float:
    """Combine one doc's normalized sub-query scores (absent sub-queries
    contribute 0, matching the neural-search processor)."""
    w = np.asarray(weights, np.float64)
    s = np.asarray(per_query, np.float64)
    if technique == "arithmetic_mean":
        return float((w * s).sum() / w.sum())
    if technique == "geometric_mean":
        # zeros collapse the product: only positive entries participate,
        # weighted geometric mean over them
        pos = s > 0
        if not pos.any():
            return 0.0
        return float(np.exp((w[pos] * np.log(s[pos])).sum() / w[pos].sum()))
    if technique == "harmonic_mean":
        pos = s > 0
        if not pos.any():
            return 0.0
        return float(w[pos].sum() / (w[pos] / s[pos]).sum())
    raise IllegalArgumentError(
        f"unknown combination technique [{technique}]")


class NormalizationConfig:
    def __init__(self, body: Optional[dict] = None):
        body = body or {}
        self.normalization = (body.get("normalization")
                              or DEFAULT_NORMALIZATION).get(
            "technique", DEFAULT_NORMALIZATION["technique"])
        if self.normalization not in ("min_max", "l2"):
            raise IllegalArgumentError(
                f"unknown normalization technique [{self.normalization}]")
        comb = body.get("combination") or DEFAULT_COMBINATION
        self.combination = comb.get("technique", "arithmetic_mean")
        if self.combination not in ("arithmetic_mean", "geometric_mean",
                                    "harmonic_mean"):
            raise IllegalArgumentError(
                f"unknown combination technique [{self.combination}]")
        self.weights = (comb.get("parameters") or {}).get("weights")
        if self.weights is not None:
            if (not isinstance(self.weights, list)
                    or any(not isinstance(w, (int, float)) or w < 0
                           for w in self.weights)
                    or sum(self.weights) <= 0):
                raise IllegalArgumentError(
                    "combination weights must be non-negative numbers "
                    "with a positive sum")

    def apply(self, per_query_rows: list[list[dict]], k: int) -> list[dict]:
        """``per_query_rows``: one row list per sub-query (rows carry
        seg/local/score).  Returns the combined, re-sorted row list."""
        nq = len(per_query_rows)
        weights = self.weights or [1.0] * nq
        if len(weights) != nq:
            raise ValidationError(
                f"combination weights has {len(weights)} entries for "
                f"{nq} sub-queries")
        normalized: dict[tuple, list[float]] = {}
        for qi, rows in enumerate(per_query_rows):
            scores = np.asarray([r["score"] for r in rows], np.float64)
            norm = normalize_scores(scores, self.normalization)
            for r, ns in zip(rows, norm):
                key = (r["seg"], r["local"])
                slot = normalized.setdefault(key, [0.0] * nq)
                slot[qi] = float(ns)
        combined = []
        for (seg, local), per_q in normalized.items():
            combined.append({
                "seg": seg, "local": local,
                "score": combine_scores(per_q, weights, self.combination)})
        combined.sort(key=lambda r: (-r["score"], r["seg"], r["local"]))
        return combined[:k]


_KNOWN_PROCESSORS = ("normalization-processor",)
_PROCESSOR_META_KEYS = ("tag", "description", "ignore_failure")


def _processor_of(entry) -> tuple[str, dict]:
    """(name, config) of one processor entry; meta keys (tag/...) are
    allowed alongside; anything else is a client error, never a crash."""
    if not isinstance(entry, dict):
        raise IllegalArgumentError(
            f"processor entry must be an object, got "
            f"[{type(entry).__name__}]")
    names = [k for k in entry if k not in _PROCESSOR_META_KEYS]
    if len(names) != 1:
        raise IllegalArgumentError(
            f"processor entry must have exactly one processor type, "
            f"got {names}")
    name = names[0]
    if name not in _KNOWN_PROCESSORS:
        raise IllegalArgumentError(
            f"unknown phase_results processor [{name}] — supported: "
            f"{list(_KNOWN_PROCESSORS)}")
    conf = entry[name]
    if conf is not None and not isinstance(conf, dict):
        raise IllegalArgumentError(
            f"processor [{name}] config must be an object")
    return name, conf or {}


class SearchPipelineService:
    """Named-pipeline registry with on-disk persistence (the cluster-state
    storage of the reference, node-local here)."""

    def __init__(self, data_path: str):
        self._file = os.path.join(data_path, "search_pipelines.json")
        self._lock = threading.Lock()
        self._pipelines: dict[str, dict] = {}
        if os.path.exists(self._file):
            with open(self._file) as f:
                self._pipelines = json.load(f)

    def _persist(self):
        tmp = self._file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._pipelines, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._file)

    def put(self, pipeline_id: str, body: dict) -> dict:
        for p in body.get("phase_results_processors") or []:
            _name, conf = _processor_of(p)
            NormalizationConfig(conf)     # validates techniques eagerly
        with self._lock:
            self._pipelines[pipeline_id] = body
            self._persist()
        return {"acknowledged": True}

    def get(self, pipeline_id: Optional[str] = None) -> dict:
        with self._lock:
            if pipeline_id is None:
                return dict(self._pipelines)
            if pipeline_id not in self._pipelines:
                raise PipelineMissingError(
                    f"search pipeline [{pipeline_id}] not found")
            return {pipeline_id: self._pipelines[pipeline_id]}

    def delete(self, pipeline_id: str) -> dict:
        with self._lock:
            if pipeline_id not in self._pipelines:
                raise PipelineMissingError(
                    f"search pipeline [{pipeline_id}] not found")
            del self._pipelines[pipeline_id]
            self._persist()
        return {"acknowledged": True}

    def hybrid_conf(self, pipeline_id: str) -> Optional[dict]:
        """The named pipeline's normalization-processor config dict (the
        value the REST layer threads to _hybrid_search), or None when
        the pipeline has no such processor."""
        body = self.get(pipeline_id)[pipeline_id]
        for p in body.get("phase_results_processors") or []:
            name, conf = _processor_of(p)
            if name == "normalization-processor":
                return conf
        return None
