"""Shard-side query phase: run a compiled plan over every segment, merge
top-k across segments, fetch sources.

Analog of ``SearchService.executeQueryPhase`` -> ``QueryPhase.execute``
(search/query/QueryPhase.java:133) and the per-leaf loop in
``ContextIndexSearcher.searchLeaf`` (search/internal/
ContextIndexSearcher.java:292).  Where Lucene iterates doc-at-a-time per
leaf, here each segment is one batched XLA program producing dense scores;
the per-shard "reduce" over segments is a host-side k-way merge with
Lucene's tie-break (score desc, then index order = (segment, local doc)).
"""

from __future__ import annotations

import functools
import json
import time
from typing import Optional

import numpy as np

import opensearch_tpu.common.jaxenv  # noqa: F401
import jax.numpy as jnp

from opensearch_tpu.common.errors import IllegalArgumentError
from opensearch_tpu.common.telemetry import metrics as _metrics
from opensearch_tpu.common.telemetry import tracer as _tracer
from opensearch_tpu.index.segment import (
    LONG_MISSING_MAX,
    LONG_MISSING_MIN,
    DeviceSegment,
    Segment,
)
from opensearch_tpu.ops import bm25 as bm25_ops
from opensearch_tpu.search import insights
from opensearch_tpu.search import plan as P
from opensearch_tpu.search.compiler import ShardContext, compile_query
from opensearch_tpu.search.fetch import filter_source
from opensearch_tpu.search.query_dsl import parse_query

_F32 = np.float32
_I32 = np.int32
_I32_MAX = 2**31 - 1

# Cluster-wide default for ``allow_partial_search_results`` (the
# reference's dynamic ``search.default_allow_partial_search_results``
# setting): a request-level value wins; the REST layer updates this via
# _cluster/settings, and the cluster coordinator reads it at scatter
# time.  True = a dead shard copy degrades the response
# (``_shards.failed`` + ``failures[]``) instead of failing it.
DEFAULT_ALLOW_PARTIAL_RESULTS = True


def shards_section(total: int, failures: "Optional[list]" = None,
                   skipped: int = 0) -> dict:
    """The ``_shards`` response block, with the reference's shape: a
    ``failures`` array only when something actually failed."""
    failures = failures or []
    out = {"total": int(total),
           "successful": int(total) - len(failures),
           "skipped": int(skipped), "failed": len(failures)}
    if failures:
        out["failures"] = list(failures)
    return out


def shard_failure_entry(index: str, shard: int, node: "Optional[str]",
                        exc: BaseException) -> dict:
    """One ``_shards.failures[]`` element (ShardSearchFailure analog):
    carries the REMOTE error type when the failure crossed the wire."""
    err_type = getattr(exc, "remote_type", None) \
        or getattr(exc, "error_type", None) \
        or type(exc).__name__
    return {"shard": int(shard), "index": index, "node": node,
            "reason": {"type": err_type, "reason": str(exc)}}


class SearchDeadline:
    """Per-request time budget (QueryPhase's timeout runnable analog).

    Checked between per-segment device programs — the same granularity
    as cancellation.  When the budget expires the query phase stops
    launching segments and the response carries ``timed_out: true`` with
    the partial results collected so far, like the reference's
    TimeExceededException handling in QueryPhase.execute.
    """

    __slots__ = ("_deadline", "timed_out")

    def __init__(self, timeout, t0: Optional[float] = None):
        """``timeout``: "100ms"/"2s"-style or millis; None disables."""
        self.timed_out = False
        if timeout is None:
            self._deadline = None
            return
        from opensearch_tpu.common.settings import parse_time
        seconds = parse_time(timeout)
        self._deadline = (None if seconds < 0
                          else (t0 if t0 is not None
                                else time.monotonic()) + seconds)

    def expired(self) -> bool:
        """True once the budget is spent; latches ``timed_out``."""
        if self._deadline is not None and \
                time.monotonic() >= self._deadline:
            self.timed_out = True
        return self.timed_out


def _dummy_for(group: str, field: str, dseg: DeviceSegment, mapper):
    """Shape-consistent empty arrays for a field absent from this segment
    (all-inactive: matches nothing, scores nothing)."""
    n_pad = dseg.n_pad
    dead = n_pad - 1
    if group == "postings":
        return {
            "offsets": jnp.zeros(8, jnp.int32),
            "doc_ids": jnp.full(8, dead, jnp.int32),
            "tfs": jnp.zeros(8, jnp.float32),
            "doc_lens": jnp.zeros(n_pad, jnp.float32),
            "pos_offsets": jnp.zeros(8, jnp.int32),
            "positions": jnp.zeros(8, jnp.int32),
            "field_exists": jnp.zeros(n_pad, bool),
        }
    if group == "numeric":
        ft = mapper.field_type(field)
        dtype = jnp.float64 if (ft is not None and ft.dv_kind == "double") else jnp.int64
        sentinel_min = np.inf if dtype == jnp.float64 else LONG_MISSING_MAX
        sentinel_max = -np.inf if dtype == jnp.float64 else LONG_MISSING_MIN
        return {
            "values": jnp.zeros(8, dtype),
            "value_docs": jnp.full(8, dead, jnp.int32),
            "minv": jnp.full(n_pad, sentinel_min, dtype),
            "maxv": jnp.full(n_pad, sentinel_max, dtype),
            "exists": jnp.zeros(n_pad, bool),
        }
    if group == "ordinal":
        return {
            "ords": jnp.full(8, -1, jnp.int32),
            "value_docs": jnp.full(8, dead, jnp.int32),
            "min_ord": jnp.full(n_pad, -1, jnp.int32),
            "max_ord": jnp.full(n_pad, -1, jnp.int32),
            "exists": jnp.zeros(n_pad, bool),
        }
    if group == "vector":
        ft = mapper.field_type(field)
        dim = getattr(ft, "dims", 1) or 1
        return {
            "values": jnp.zeros((n_pad, dim), jnp.float32),
            "exists": jnp.zeros(n_pad, bool),
        }
    if group == "geo":
        return {
            "lats": jnp.zeros(8, jnp.float32),
            "lons": jnp.zeros(8, jnp.float32),
            "value_docs": jnp.full(8, dead, jnp.int32),
            "exists": jnp.zeros(n_pad, bool),
        }
    raise IllegalArgumentError(f"unknown array group [{group}]")


def build_arrays(dseg: DeviceSegment, needed, mapper, live=None,
                 partial_ok=frozenset()):
    """Assemble the ``A`` pytree a plan reads: live mask + requested field
    array groups (absent fields get all-inactive dummies).  ``live`` is the
    caller's point-in-time staged live mask (defaults to the segment's
    construction-time state).

    ``partial_ok`` is the plan's ``skip_arrays(dims)`` — (group, field)
    pairs whose partial staging is fine as-is.  Quantized segments stage
    only offsets/doc_lens/field_exists eagerly; any OTHER plan touching
    their postings demand-stages the full f32 columns here
    (``DeviceSegment.ensure_postings``)."""
    from opensearch_tpu.common.cache import attached_cache

    A = {"live": dseg.live if live is None else live}
    sources = {"postings": dseg.postings, "numeric": dseg.numeric,
               "ordinal": dseg.ordinal, "vector": dseg.vector,
               "geo": dseg.geo}
    # per-device-segment dummy-array cache: bounded + accounted against
    # the fielddata breaker (these live in device memory with the real
    # columns); the weakref finalizer releases the accounting when the
    # staging is dropped
    cache = attached_cache(dseg, "_dummy_cache",
                           name="query.dummy_arrays",
                           max_weight=32 << 20, breaker="fielddata")
    for group, field in sorted(needed):
        entry = sources[group].get(field)
        if entry is None:
            entry = cache.get((group, field))
            if entry is None:
                entry = _dummy_for(group, field, dseg, mapper)
                cache.put((group, field), entry)
        elif (group == "postings" and "doc_ids" not in entry
                and (group, field) not in partial_ok):
            entry = dseg.ensure_postings(field)
        A.setdefault(group, {})[field] = {
            k: v for k, v in entry.items() if k != "n_ords"}
    return A


def _parse_sort(spec) -> Optional[list[dict]]:
    """Normalize the request ``sort`` into [{field, order, missing}].
    Returns None for the plain score-sorted path."""
    if spec is None:
        return None
    if not isinstance(spec, list):
        spec = [spec]
    out = []
    for s in spec:
        if isinstance(s, str):
            field, order = s, ("desc" if s == "_score" else "asc")
            out.append({"field": field, "order": order, "missing": "_last"})
        elif isinstance(s, dict):
            if len(s) != 1:
                raise IllegalArgumentError(f"malformed sort clause {s}")
            field, opts = next(iter(s.items()))
            if isinstance(opts, str):
                out.append({"field": field, "order": opts, "missing": "_last"})
            else:
                out.append({"field": field,
                            "order": opts.get("order",
                                              "desc" if field == "_score" else "asc"),
                            "missing": opts.get("missing", "_last")})
        else:
            raise IllegalArgumentError(f"malformed sort clause {s}")
    if len(out) == 1 and out[0]["field"] == "_score" and out[0]["order"] == "desc":
        return None
    return out


_MS_NEG_INF = None


def _min_score_scalar(min_score):
    """Staged min_score scalar; the common None case reuses one device
    constant instead of a fresh 4-byte H2D transfer per query."""
    global _MS_NEG_INF
    if min_score is None:
        if _MS_NEG_INF is None:
            # staging-ok: one cached 4-byte scalar constant
            _MS_NEG_INF = jnp.asarray(np.float32(-np.inf))
        return _MS_NEG_INF
    return jnp.asarray(np.float32(min_score))  # staging-ok: 4-byte scalar


def _ledger():
    from opensearch_tpu.common.device_ledger import device_ledger
    return device_ledger()


def _health():
    from opensearch_tpu.common.device_health import device_health
    return device_health()


class ShardSearcher:
    """Immutable point-in-time view over a shard's segments (the
    Engine.Searcher / reader-context analog, ref search/SearchService.java:986)."""

    def __init__(self, segments: list[Segment], mapper, index_name: str = "index",
                 shard_id: int = 0):
        self.segments = [s for s in segments if s.n_docs > 0]
        self.mapper = mapper
        self.index_name = index_name
        self.shard_id = shard_id
        self.ctx = ShardContext(self.segments, mapper)

    # -- compiled-plan / prepared-bindings caches -------------------------

    def compiled(self, query_json: Optional[dict], scored: bool = True,
                 with_key: bool = False, prof=None, iattrs=None):
        """(plan, bind) for a raw query body through the searcher's plan
        cache, keyed on the canonicalized JSON (key order in the body
        never misses).  The searcher is an immutable point-in-time view,
        so entries can never go stale — a refresh builds a NEW searcher
        (the PR-3 reader-generation bump) and this cache dies with the
        old one.  A repeated query shape therefore does zero
        parse/compile work (`search.plan_cache.hits`).  ``prof`` (a
        QueryProfiler) times the cache lookup / parse / compile and
        records the hit-vs-miss attribution."""
        from opensearch_tpu.common.cache import attached_cache

        t_lookup = time.monotonic() if prof is not None else 0.0
        try:
            ckey = (json.dumps(query_json, sort_keys=True,
                               separators=(",", ":")), scored)
        except (TypeError, ValueError):
            ckey = None
        if ckey is not None:
            cache = attached_cache(self, "_plan_cache",
                                   name="search.plan",
                                   max_weight=16 << 20,
                                   breaker="fielddata")
            out = cache.get(ckey)
            if prof is not None:
                prof.add("plan_cache", time.monotonic() - t_lookup)
            if out is not None:
                _metrics().counter("search.plan_cache.hits").inc()
                if prof is not None:
                    prof.set("plan_cache", "hit")
                if iattrs is not None:
                    iattrs["plan_cache"] = "hit"
                return (out, ckey) if with_key else out
        elif prof is not None:
            prof.add("plan_cache", time.monotonic() - t_lookup)
        _metrics().counter("search.plan_cache.misses").inc()
        if iattrs is not None:
            iattrs["plan_cache"] = "miss"
        if prof is not None:
            prof.set("plan_cache", "miss")
            with prof.phase("rewrite"):
                q = parse_query(query_json)
            out = compile_query(q, self.ctx, scored=scored, prof=prof)
        else:
            out = compile_query(parse_query(query_json), self.ctx,
                                scored=scored)
        if ckey is not None:
            cache.put(ckey, out)
        return (out, ckey) if with_key else out

    @staticmethod
    def _prep_weight(key, value) -> int:
        """Prepared-bindings weigher: large staged columns referenced
        from the ins pytree (impacts et al.) are owned and accounted by
        the device-segment caches — charging their full nbytes here
        would thrash the cache on shared references, so anything over
        1 MiB is capped at 1 MiB."""
        from opensearch_tpu.common.cache import estimate_weight

        total = estimate_weight(key)

        def walk(v):
            nonlocal total
            nbytes = getattr(v, "nbytes", None)
            if nbytes is not None:
                total += min(int(nbytes), 1 << 20)
            elif isinstance(v, (tuple, list)):
                for x in v:
                    walk(x)
            elif isinstance(v, dict):
                for x in v.values():
                    walk(x)
            else:
                total += 8
        walk(value)
        return total

    def _prepared(self, plan, bind, seg, dseg, ckey, prof=None):
        """``plan.prepare``'s per-(plan, segment) static products —
        padded term ids, staged impact references, device scalars —
        cached so a repeated query shape does zero host-side prepare
        work (and zero H2D transfers) per segment.  ``prof`` records
        prepare time and the per-segment prepared-bindings hit/miss."""
        if ckey is None:
            if prof is None:
                return plan.prepare(bind, seg, dseg, self.ctx)
            prof.inc("prepared_misses")
            with prof.phase("prepare"):
                return plan.prepare(bind, seg, dseg, self.ctx)
        from opensearch_tpu.common.cache import attached_cache

        cache = attached_cache(self, "_prep_cache",
                               name="search.prepare",
                               max_weight=64 << 20, breaker="fielddata",
                               weigher=self._prep_weight)
        key = (ckey, id(seg))
        out = cache.get(key)
        if out is None:
            if prof is not None:
                prof.inc("prepared_misses")
                with prof.phase("prepare"):
                    out = plan.prepare(bind, seg, dseg, self.ctx)
            else:
                out = plan.prepare(bind, seg, dseg, self.ctx)
            cache.put(key, out)
        elif prof is not None:
            prof.inc("prepared_hits")
        return out

    # -- public API -------------------------------------------------------

    def doc_count(self) -> int:
        return sum(s.live_count() for s in self.segments)

    def count(self, query_json: Optional[dict] = None) -> int:
        if not self.segments:
            return 0
        (plan, bind), ckey = self.compiled(query_json, scored=False,
                                           with_key=True)
        needed = plan.arrays()
        total = 0
        # can_match skip is safe here: count only sums, so segments the
        # plan provably can't match contribute nothing either way
        for seg, dseg, scores, matched in self._run_full(
                plan, bind, needed, None, can_match_skip=True, ckey=ckey):
            total += int(np.asarray(matched).sum())
        return total

    def search(self, body: Optional[dict] = None, *,
               agg_partials: bool = False) -> dict:
        """``agg_partials=True`` is the distributed query phase: instead of
        finished aggregations the response carries the shard's mergeable
        ``aggregation_partials`` for a coordinator-side ``reduce_aggs``
        (QueryPhaseResultConsumer partial-reduce analog)."""
        body = body or {}
        t0 = time.monotonic()
        prof = None
        if body.get("profile"):
            # plan-time guard: the profiler exists ONLY for profiled
            # requests; every downstream instrumentation point checks
            # ``prof is not None`` (zero cost when profile is absent)
            from opensearch_tpu.search.profile import QueryProfiler
            prof = QueryProfiler()
        with _tracer().start_span(
                "shard.query_phase",
                {"index": self.index_name, "shard": self.shard_id,
                 "segments": len(self.segments)}):
            resp = self._search_body(body, t0, agg_partials=agg_partials,
                                     prof=prof)
        _metrics().histogram("search.query_ms").observe(
            (time.monotonic() - t0) * 1000)
        _metrics().counter("search.queries").inc()
        if resp.get("timed_out"):
            _metrics().counter("search.timed_out").inc()
        return resp

    def _search_body(self, body: dict, t0: float, *,
                     agg_partials: bool = False, prof=None) -> dict:
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        deadline = SearchDeadline(body.get("timeout"), t0)
        q_json = body.get("query")
        fetch_extras = None
        # request-size limits (docvalue_fields, rescore window, result
        # window) are enforced by IndexService._check_search_limits with
        # the index's own settings; the shard searcher stays policy-free
        if (body.get("highlight") or body.get("explain")
                or body.get("docvalue_fields") or body.get("fields")):
            fetch_extras = {"highlight": body.get("highlight"),
                            "explain": bool(body.get("explain")),
                            "docvalue_fields": body.get("docvalue_fields"),
                            "fields": body.get("fields"),
                            "query": parse_query(q_json)}
        if isinstance(q_json, dict) and "hybrid" in q_json:
            from opensearch_tpu.search.query_dsl import HybridQuery
            q = parse_query(q_json)
            if isinstance(q, HybridQuery):
                return self._hybrid_search(body, q, t0, fetch_extras)
        sort_specs = _parse_sort(body.get("sort"))
        min_score = body.get("min_score")
        source_spec = body.get("_source")
        stored = body.get("stored_fields")
        if stored is not None and source_spec is None:
            # legacy stored_fields: _source returns only when asked for
            # explicitly (RestSearchAction's stored-fields contract)
            if isinstance(stored, str):
                stored = [stored]
            if "_source" not in stored:
                source_spec = False
        search_after = body.get("search_after")
        if search_after is not None:
            if sort_specs is None:
                raise IllegalArgumentError(
                    "[search_after] requires an explicit [sort]")
            if not isinstance(search_after, (list, tuple)):
                raise IllegalArgumentError(
                    "[search_after] must be an array of sort values")
            if len(search_after) != len(sort_specs):
                raise IllegalArgumentError(
                    f"[search_after] has {len(search_after)} values but "
                    f"sort has {len(sort_specs)} fields")

        # field-sorted queries that never reference _score skip BM25 scoring
        needs_scores = (sort_specs is None
                        or any(s["field"] == "_score" for s in sort_specs)
                        or min_score is not None)
        # always-on insight attribution: a few dict writes per query
        # (never per segment sync), drained by whatever edge installed
        # an insight sink — see search/insights.py emit()
        ia = {"plan_cache": "miss", "pruned": 0, "scanned": 0}
        xfer0 = _ledger().transfer_snapshot()
        (plan, bind), ckey = self.compiled(q_json, scored=needs_scores,
                                           with_key=True, prof=prof,
                                           iattrs=ia)
        needed = plan.arrays()
        k_want = from_ + size
        # with exact totals waived, block-max pruning may also skip
        # segments that cannot beat the running k-th score (the
        # reference's track_total_hits=false contract: totals become a
        # lower bound, flagged with relation "gte")
        allow_kth_prune = body.get("track_total_hits") is False

        rescore = body.get("rescore")
        collapse = body.get("collapse")
        if rescore and collapse:
            raise IllegalArgumentError(
                "cannot use [collapse] in conjunction with [rescore]")
        if rescore is not None:
            if sort_specs is not None:
                raise IllegalArgumentError(
                    "rescore is only supported on score-sorted queries")
            # widen the first pass to the rescore window
            spec = rescore[0] if isinstance(rescore, list) else rescore
            k_want = max(k_want, int(spec.get("window_size", 10)))

        aggs_json = body.get("aggs") or body.get("aggregations")
        # with aggs, the full-scores pass runs ONCE and feeds both the
        # top-k and the aggregations (no second device execution)
        views = (list(self._run_full(plan, bind, needed, min_score,
                                     deadline=deadline, ckey=ckey,
                                     prof=prof, iattrs=ia))
                 if aggs_json and self.segments else None)

        total_is_lower_bound = False
        if not self.segments:
            rows, total, max_score = [], 0, None
        elif collapse is not None:
            rows, total, max_score = self._collapsed(
                plan, bind, needed, k_want, sort_specs, min_score,
                collapse, views, search_after=search_after)
        elif sort_specs is None:
            if views is not None:
                rows, total, max_score = self._topk_from_views(
                    views, k_want, prof=prof)
            else:
                rows, total, max_score, total_is_lower_bound = self._topk(
                    plan, bind, needed, k_want, min_score,
                    deadline=deadline, ckey=ckey,
                    allow_kth_prune=allow_kth_prune, prof=prof,
                    iattrs=ia)
        else:
            rows, total, max_score = self._field_sorted(
                plan, bind, needed, k_want, sort_specs, min_score, views,
                search_after=search_after, deadline=deadline, ckey=ckey,
                prof=prof)
        if rescore is not None and rows:
            rows, max_score = self._rescored(rows, rescore)
        rows = rows[from_: from_ + size]

        aggregations = partials = None
        if aggs_json:
            from opensearch_tpu.search.aggs import AggregationExecutor
            seg_views = [(seg, dseg, matched)
                         for seg, dseg, _s, matched in (views or [])]
            scores_of = {seg.seg_id: s
                         for seg, _d, s, _m in (views or [])}
            execu = AggregationExecutor(self.ctx, scores_of=scores_of)
            if agg_partials:
                partials = execu.collect(aggs_json, seg_views)
            else:
                aggregations = execu.run(aggs_json, seg_views)

        t_fetch = time.monotonic() if prof is not None else 0.0
        with _tracer().start_span("fetch_phase",
                                  {"index": self.index_name,
                                   "hits": len(rows)}), \
                _metrics().time_ms("search.fetch_ms"):
            hits = self._hits_from_rows(rows, source_spec, fetch_extras)
        if prof is not None:
            prof.add("fetch", time.monotonic() - t_fetch)

        took = int((time.monotonic() - t0) * 1000)
        xfer1 = _ledger().transfer_snapshot()
        insights.emit(
            signature=ckey[0] if ckey is not None else None,
            scored=needs_scores,
            took_ms=(time.monotonic() - t0) * 1000,
            execution_path=ia.get(
                "execution_path",
                "host" if (bm25_ops.host_scoring_enabled()
                           and getattr(plan, "scored", False)
                           and getattr(plan, "host_topk", None)
                           is not None) else "device"),
            plan_cache=ia["plan_cache"],
            pruned=ia["pruned"], scanned=ia["scanned"],
            transfer_bytes=(xfer1[0] - xfer0[0]) + (xfer1[1] - xfer0[1]),
            timed_out=deadline.timed_out)
        resp = {
            "took": took,
            "timed_out": deadline.timed_out,
            "_shards": shards_section(1),
            "hits": {
                "total": {"value": int(total),
                          "relation": ("gte" if total_is_lower_bound
                                       else "eq")},
                "max_score": max_score,
                "hits": hits,
            },
        }
        if prof is not None:
            # real phase-attributed profile (search/profile/query/
            # QueryProfiler analog at program granularity: the device
            # runs fused programs, so per-collector callbacks don't
            # exist — phases are the host-side stages around them)
            from opensearch_tpu.search.profile import describe_plan
            resp["profile"] = {"shards": [prof.shard_section(
                self.index_name, self.shard_id,
                plan_type=type(plan).__name__,
                description=describe_plan(plan, bind),
                total_segments=len(self.segments))]}
        if aggregations is not None:
            resp["aggregations"] = aggregations
        if partials is not None:
            resp["aggregation_partials"] = partials
        if body.get("suggest"):
            from opensearch_tpu.search.suggest import run_suggest
            resp["suggest"] = run_suggest(body["suggest"], self.ctx)
            for entries in resp["suggest"].values():
                for entry in entries:
                    for opt in entry.get("options", ()):
                        if "_id" in opt and "_index" not in opt:
                            opt["_index"] = self.index_name
        return resp

    def _hybrid_search(self, body: dict, q, t0,
                       fetch_extras=None) -> dict:
        """Hybrid query: each sub-query runs as its own device program;
        the normalization processor (search/pipeline.py) combines the
        per-sub-query top lists host-side.  ``_hybrid_pipeline`` in the
        body carries the processor config (wired by the REST layer from
        ?search_pipeline=...); absent -> min_max + arithmetic_mean."""
        from opensearch_tpu.common.errors import ValidationError
        from opensearch_tpu.search.pipeline import NormalizationConfig

        if (body.get("sort") is not None or body.get("aggs")
                or body.get("aggregations")
                or body.get("min_score") is not None
                or body.get("search_after") is not None):
            raise ValidationError(
                "[hybrid] query does not support [sort], [aggs], "
                "[min_score] or [search_after]")
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        k_want = from_ + size
        deadline = SearchDeadline(body.get("timeout"), t0)
        conf = NormalizationConfig(body.get("_hybrid_pipeline"))
        per_query_rows = []
        max_total = 0
        for subq in q.queries:
            if deadline.expired():
                break            # partial: combine what completed
            plan, bind = compile_query(subq, self.ctx, scored=True)
            rows, tot, _mx, _lb = self._topk(plan, bind, plan.arrays(),
                                             k_want, None,
                                             deadline=deadline)
            per_query_rows.append(rows)
            max_total = max(max_total, int(tot))
        combined = conf.apply(per_query_rows, k_want)
        rows = combined[from_: from_ + size]
        hits = self._hits_from_rows(rows, body.get("_source"),
                                    fetch_extras)
        insights.emit(
            signature=insights.canonical_query(body.get("query")),
            scored=True,
            took_ms=(time.monotonic() - t0) * 1000,
            execution_path="device", plan_cache="miss",
            timed_out=deadline.timed_out)
        # per-sub-query top-k truncation means the union is a lower
        # bound beyond the largest sub-query's exact count
        return {
            "took": int((time.monotonic() - t0) * 1000),
            "timed_out": deadline.timed_out,
            "_shards": shards_section(1),
            "hits": {"total": {"value": max_total, "relation": "gte"},
                     "max_score": (combined[0]["score"] if combined
                                   else None),
                     "hits": hits},
        }

    def msearch(self, bodies: list) -> list[dict]:
        """Multi-search (the ``_msearch`` analog): bodies that compile to a
        scored term-bag run as ONE batched device program per (field, k,
        segment) — Q queries per dispatch instead of Q dispatches (see
        search/batch.py); everything else runs the normal path.  Response
        order matches request order."""
        import time

        from opensearch_tpu.search.batch import plan_batches

        t0 = time.monotonic()
        if not self.segments:
            return [self.search(b) for b in bodies]
        groups, fallback = plan_batches(self, bodies)
        results: list = [None] * len(bodies)
        for g in groups.values():
            gprof = None
            if any((bodies[p] or {}).get("profile") for p in g.positions):
                # ONE profiler per coalesced group: members share the
                # group's phase timings by construction (that sharing IS
                # the batch-coalescing attribution)
                from opensearch_tpu.search.profile import QueryProfiler
                gprof = QueryProfiler()
                # members were parsed/compiled during batch planning
                # (through the plan cache, counted in the
                # search.plan_cache.* metrics) — per-member hit/miss is
                # not attributable after coalescing
                gprof.set("plan_cache", "batched")
                gprof.set("batch", {
                    "field": g.field, "k": g.k,
                    "queries": len(g.positions),
                    "positions": list(g.positions)})
            xfer0 = _ledger().transfer_snapshot()
            g_out = g.run(self, prof=gprof)
            xfer1 = _ledger().transfer_snapshot()
            # ONE batched pass served the whole group: its transfer
            # bytes are shared group attribution, like last_stats
            g_xfer = (xfer1[0] - xfer0[0]) + (xfer1[1] - xfer0[1])
            for pos, (rows, total, max_score) in g_out.items():
                body = bodies[pos] or {}
                t_fetch = time.monotonic() if gprof is not None else 0.0
                hits = self._hits_from_rows(rows, body.get("_source"))
                if gprof is not None:
                    gprof.add("fetch", time.monotonic() - t_fetch)
                # batched bodies never carry a [timeout] (plan_batches
                # sends those to the sequential fallback, which owns the
                # deadline checks), so false is exact here
                results[pos] = {
                    "took": int((time.monotonic() - t0) * 1000),
                    "timed_out": False,
                    "_shards": shards_section(1),
                    "hits": {"total": {"value": int(total),
                                       "relation": "eq"},
                             "max_score": max_score, "hits": hits},
                }
                # one insight record per coalesced member: its OWN plan
                # signature (members of a (field, k) group still differ
                # by terms) + the group size — the measured coalescing
                # the continuous batcher's sizing report aggregates
                insights.emit(
                    signature=insights.canonical_query(
                        body.get("query")),
                    scored=True,
                    took_ms=(time.monotonic() - t0) * 1000,
                    execution_path=(
                        "host_batched"
                        if bm25_ops.host_scoring_enabled()
                        else "device_batched"),
                    plan_cache="batched",
                    pruned=g.last_stats["pruned"],
                    scanned=g.last_stats["scanned"],
                    transfer_bytes=g_xfer,
                    batched=len(g.positions))
                if gprof is not None and body.get("profile"):
                    results[pos]["profile"] = {"shards": [
                        gprof.shard_section(
                            self.index_name, self.shard_id,
                            plan_type="TermBagPlan",
                            description=(f"batched[{g.field}] "
                                         f"member {pos} of "
                                         f"{len(g.positions)}"),
                            total_segments=len(self.segments))]}
        if len(fallback) > 1:
            # non-coalescable members fan out over the engine's bounded
            # search threadpool — the sequential host fast path
            # parallelizes across cores instead of serializing behind
            # one request thread (overflow runs inline, same semantics)
            from opensearch_tpu.search.engine import query_engine
            outs = query_engine().pool.run_all(
                [(lambda b=bodies[pos]: self.search(b))
                 for pos in fallback])
            for pos, r in zip(fallback, outs):
                results[pos] = r
        else:
            for pos in fallback:
                results[pos] = self.search(bodies[pos])
        return results

    def _hits_from_rows(self, rows, source_spec, fetch_extras=None):
        from opensearch_tpu.search.fetch import (docvalue_fields,
                                                 explain_hit,
                                                 fields_option,
                                                 run_highlight)

        hits = []
        for row in rows:
            seg = self.segments[row["seg"]]
            local = row["local"]
            hit = {"_index": self.index_name, "_id": seg.doc_ids[local],
                   "_score": row.get("score")}
            source = seg.source(local)
            src = filter_source(source, source_spec)
            if src is not None:
                hit["_source"] = src
            if "sort" in row:
                hit["sort"] = row["sort"]
            if "fields" in row:            # collapse key et al.
                hit["fields"] = dict(row["fields"])
            if fetch_extras is not None:
                if fetch_extras.get("highlight"):
                    hl = run_highlight(fetch_extras["highlight"], source,
                                       fetch_extras["query"], self.mapper)
                    if hl:
                        hit["highlight"] = hl
                fields = {}
                if fetch_extras.get("docvalue_fields"):
                    fields.update(docvalue_fields(
                        fetch_extras["docvalue_fields"], seg, local,
                        self.mapper))
                if fetch_extras.get("fields"):
                    fields.update(fields_option(fetch_extras["fields"],
                                                source))
                if fields:
                    hit["fields"] = fields
                if fetch_extras.get("explain"):
                    hit["_explanation"] = explain_hit(
                        row.get("score"), fetch_extras["query"], seg,
                        local, self.ctx)
            hits.append(hit)
        return hits

    # -- internals --------------------------------------------------------

    def _run_full(self, plan, bind, needed, min_score,
                  can_match_skip=False, deadline=None, ckey=None,
                  prof=None, iattrs=None):
        """``can_match_skip`` is ONLY safe for consumers that don't index
        the yielded tuples by position (views/aggs paths align with
        self.segments and must see every segment).  An expired
        ``deadline`` stops the scan at the next segment boundary — the
        same granularity as cancellation."""
        from opensearch_tpu.common.device_health import (
            DeviceDegradedError, is_device_error)
        from opensearch_tpu.common.tasks import check_current

        health = _health()
        if not (health.allow("dispatch") and health.allow("staging")):
            # full-scores plans have no host fallback: while the device
            # breaker is open they degrade into PR-2-style partial
            # _shards.failures[] at the caller instead of dispatching
            # onto a failing accelerator (or returning a 500)
            raise DeviceDegradedError(
                "device circuit breaker open: full-scores plan "
                f"[{type(plan).__name__}] has no host fallback")
        ms = _min_score_scalar(min_score)
        for seg in self.segments:
            check_current()        # cancellation point per segment program
            if deadline is not None and deadline.expired():
                return
            t_seg = time.monotonic() if prof is not None else 0.0
            if can_match_skip and not plan.can_match(bind, seg):
                _metrics().counter("search.segments_pruned").inc()
                if iattrs is not None:
                    iattrs["pruned"] += 1
                if prof is not None:
                    prof.seg_pruned(seg.seg_id, "pruned_can_match",
                                    time.monotonic() - t_seg)
                continue
            # phases stay disjoint: prepare time is measured inside
            # _prepared, so the dispatch share is the remainder
            prep0 = (prof.phases.get("prepare", 0.0)
                     if prof is not None else 0.0)
            with _tracer().start_span(
                    "segment.dispatch",
                    {"segment": seg.seg_id, "index": self.index_name,
                     "shard": self.shard_id}):
                try:
                    dseg = seg.device()
                    # prepare FIRST: dims tells build_arrays which
                    # array groups the lowering left deliberately
                    # partial (quantized segments)
                    dims, ins = self._prepared(plan, bind, seg, dseg,
                                               ckey, prof=prof)
                    A = build_arrays(dseg, needed, self.mapper,
                                     live=self.ctx.live_jnp(seg, dseg),
                                     partial_ok=plan.skip_arrays(dims))
                    scores, matched = P.run_full(plan, dims, A, ins, ms)
                except Exception as exc:
                    if not is_device_error(exc):
                        raise
                    # counted via record_failure -> device.errors (and
                    # device.restage_failures at the staging site)
                    health.record_failure("dispatch", exc)
                    raise DeviceDegradedError(
                        f"device failure on segment [{seg.seg_id}]: "
                        f"{type(exc).__name__}: {exc}") from exc
            health.record_success("dispatch")
            _ledger().record_dispatch(
                getattr(dseg, "_ledger_group", None))
            if iattrs is not None:
                iattrs["scanned"] += 1
            if prof is not None:
                prof.seg_scanned(seg.seg_id, max(
                    0.0, time.monotonic() - t_seg
                    - (prof.phases.get("prepare", 0.0) - prep0)))
            yield seg, dseg, scores, matched

    def _merge_topk(self, per_seg, k_want, total, max_score):
        from opensearch_tpu.common.tasks import charge_current

        if not per_seg:
            return [], 0, None
        scores = np.concatenate([p[0] for p in per_seg])
        segi = np.concatenate([p[1] for p in per_seg])
        local = np.concatenate([p[2] for p in per_seg])
        # the host-side merge buffers are this task's transient heap:
        # charged to the request breaker (released at task unregister)
        # so the backpressure service can rank queries by real cost
        charge_current(scores.nbytes + segi.nbytes + local.nbytes,
                       "search top-k merge")
        order = np.lexsort((local, segi, -scores))[:k_want]
        rows = [{"seg": int(segi[i]), "local": int(local[i]),
                 "score": float(scores[i])} for i in order]
        return rows, total, (None if max_score == -np.inf else float(max_score))

    def _topk(self, plan, bind, needed, k_want, min_score, deadline=None,
              ckey=None, allow_kth_prune=False, prof=None, iattrs=None):
        """Returns (rows, total, max_score, total_is_lower_bound).

        Block-max pruning: segments whose ``plan.max_score_bound`` can't
        reach ``min_score`` are skipped exactly (such docs are excluded
        from hits AND totals anyway).  With ``allow_kth_prune`` (the
        request waived exact totals via track_total_hits=false),
        segments that can't beat the running k-th score are skipped too
        — the k-th score is harvested opportunistically from programs
        that already finished, never blocking the async dispatch
        pipeline."""
        from opensearch_tpu.common.device_health import (
            DeviceDegradedError, is_device_error)
        from opensearch_tpu.common.tasks import check_current

        health = _health()

        if k_want == 0:            # size=0: counts only (aggs-style request)
            inner = ("can_match", "dispatch", "prepare")
            if prof is not None:
                t_red = time.monotonic()
                spent0 = sum(prof.phases.get(p, 0.0) for p in inner)
            total = sum(int(np.asarray(m).sum()) for _s, _d, _sc, m
                        in self._run_full(plan, bind, needed, min_score,
                                          can_match_skip=True,
                                          deadline=deadline, ckey=ckey,
                                          prof=prof, iattrs=iattrs))
            if prof is not None:
                # the generator's own phases were recorded inline; the
                # residual host-side sum is the reduce share
                spent = sum(prof.phases.get(p, 0.0)
                            for p in inner) - spent0
                prof.add("reduce", max(
                    0.0, time.monotonic() - t_red - spent))
            return [], total, None, False

        # phase 1: DISPATCH every segment's program without a host sync —
        # jax's async dispatch runs them back to back on the device while
        # the host prepares the next segment (the concurrent-segment-
        # search answer in the XLA model; ref search/query/
        # ConcurrentQueryPhaseSearcher.java gets the same overlap from
        # slice threads)
        ms = _min_score_scalar(min_score)
        ms_host = None if min_score is None else float(min_score)
        # CPU-backend fast path: scored term bags run host-side over the
        # precomputed impact tables (see ops/bm25.py host_scoring_enabled)
        host_capable = (getattr(plan, "scored", False)
                        and getattr(plan, "host_topk", None) is not None)
        host_fast = bm25_ops.host_scoring_enabled() and host_capable
        if iattrs is not None:
            iattrs["execution_path"] = "host" if host_fast else "device"
        if prof is not None:
            prof.set("execution_path", "host" if host_fast else "device")
        if (host_fast and prof is None and not allow_kth_prune
                and (deadline is None or deadline._deadline is None)
                and len(self.segments) > 1):
            # multi-segment host fast path: per-segment scoring is pure
            # host work with no async-dispatch overlap to exploit, so it
            # fans out across cores on the engine threadpool instead of
            # serializing on this thread.  Gated off the paths whose
            # semantics are scan-order-dependent (k-th-score pruning,
            # deadlines) and off profiled requests (exact per-phase
            # attribution) — those keep the sequential loop below.
            return self._topk_host_parallel(plan, bind, k_want,
                                            min_score, ms_host, iattrs)
        if not host_fast and hasattr(plan, "prefetch_quantized"):
            # pager prefetch oracle: best-bound-first staging of
            # quantized pages into FREE capacity before the dispatch
            # loop.  Best-effort by construction — a prefetch failure
            # surfaces (and is handled) at the segment's own dispatch
            try:
                plan.prefetch_quantized(bind, self.segments)
            except Exception:
                pass
        launched = []              # [si, vals, idx, tot, mx, synced_vals]
        kth = None                 # running k-th best (harvested, host)
        total_is_lower_bound = False
        for si, seg in enumerate(self.segments):
            check_current()        # cancellation point per segment program
            if deadline is not None and deadline.expired():
                break              # partial top-k; response flags timed_out
            t_seg = time.monotonic() if prof is not None else 0.0
            if not plan.can_match(bind, seg):
                _metrics().counter("search.segments_pruned").inc()
                if iattrs is not None:
                    iattrs["pruned"] += 1
                if prof is not None:
                    prof.seg_pruned(seg.seg_id, "pruned_can_match",
                                    time.monotonic() - t_seg)
                continue           # can-match skip: no staging, no program
            if ms_host is not None or kth is not None:
                bound = plan.max_score_bound(bind, seg)
                if ms_host is not None and bound < ms_host:
                    # exact: docs below min_score never count in totals
                    _metrics().counter("search.segments_pruned").inc()
                    if iattrs is not None:
                        iattrs["pruned"] += 1
                    if prof is not None:
                        prof.seg_pruned(seg.seg_id, "pruned_min_score",
                                        time.monotonic() - t_seg)
                    continue
                if kth is not None and bound <= kth:
                    # the k-th holder dispatched earlier, so it wins any
                    # tie at exactly `bound` (seg-asc tie-break); totals
                    # become a lower bound
                    _metrics().counter("search.segments_pruned").inc()
                    if iattrs is not None:
                        iattrs["pruned"] += 1
                    if prof is not None:
                        prof.seg_pruned(seg.seg_id, "pruned_kth",
                                        time.monotonic() - t_seg)
                    total_is_lower_bound = True
                    continue
            if prof is not None:
                # decision cost so far is can_match; the dispatch share
                # starts here and excludes _prepared's own prepare phase
                prof.add("can_match", time.monotonic() - t_seg)
                t_disp = time.monotonic()
                prep0 = prof.phases.get("prepare", 0.0)
            with _tracer().start_span(
                    "segment.dispatch",
                    {"segment": seg.seg_id, "index": self.index_name,
                     "shard": self.shard_id}):
                # budget-evicted segments — and segments behind an OPEN
                # device circuit breaker (common/device_health.py) —
                # degrade to the SAME host impact-table scoring the CPU
                # fast path uses: byte-identical to the device kernel
                # (the PR-5 invariant), so eviction/breaker-open never
                # changes results, only where they are computed
                device_ok = (health.allow("dispatch")
                             and health.allow("staging"))
                use_host = host_fast or (
                    host_capable
                    and (getattr(seg, "_device_evicted", False)
                         or not device_ok))
                if use_host:
                    if not host_fast:
                        _ledger().record_host_fallback()
                    vals, idx, tot, mx = plan.host_topk(  # engine-ok: host fast-path backend
                        bind, seg, self.ctx.lives[id(seg)],
                        min(k_want, seg.n_docs), min_score)
                    launched.append([si, vals, idx, tot, mx, vals])
                elif not device_ok:
                    raise DeviceDegradedError(
                        "device circuit breaker open: plan "
                        f"[{type(plan).__name__}] has no host fallback")
                else:
                    try:
                        dseg = seg.device()
                        # prepare FIRST so dims can mark the quantized
                        # lowering's deliberately-partial array groups
                        dims, ins = self._prepared(plan, bind, seg,
                                                   dseg, ckey, prof=prof)
                        A = build_arrays(dseg, needed, self.mapper,
                                         live=self.ctx.live_jnp(seg,
                                                                dseg),
                                         partial_ok=plan.skip_arrays(
                                             dims))
                        k = min(k_want, dseg.n_pad)
                        launched.append([si, *P.run_topk(plan, dims, k,
                                                         A, ins, ms),
                                         None])
                        _ledger().record_dispatch(
                            getattr(dseg, "_ledger_group", None))
                    except Exception as exc:
                        if not is_device_error(exc):
                            raise
                        # counted: record_failure -> device.errors (the
                        # staging site also counts restage_failures)
                        health.record_failure("dispatch", exc)
                        if not host_capable:
                            raise DeviceDegradedError(
                                "device failure on segment "
                                f"[{seg.seg_id}]: "
                                f"{type(exc).__name__}: {exc}") from exc
                        # degrade THIS segment to the byte-identical
                        # host impact-table path; the breaker decides
                        # whether later segments even try the device
                        _ledger().record_host_fallback()
                        vals, idx, tot, mx = plan.host_topk(  # engine-ok: host degrade backend
                            bind, seg, self.ctx.lives[id(seg)],
                            min(k_want, seg.n_docs), min_score)
                        launched.append([si, vals, idx, tot, mx, vals])
            if iattrs is not None:
                iattrs["scanned"] += 1
            if prof is not None:
                prof.seg_scanned(seg.seg_id, max(
                    0.0, time.monotonic() - t_disp
                    - (prof.phases.get("prepare", 0.0) - prep0)))
            if allow_kth_prune and len(launched) >= 1 \
                    and si + 1 < len(self.segments):
                kth = self._harvest_kth(launched, k_want, kth)
        # phase 2: ONE host-sync region over all segments' results —
        # also the result-sanity guard: non-finite device scores are
        # poison (a misbehaving accelerator, not a query property);
        # they are discarded, recomputed on the host byte-identically,
        # and filed as flight-recorder evidence
        from opensearch_tpu.common.device_health import check_finite
        t_sync = time.monotonic()
        t_red = t_sync if prof is not None else 0.0
        per_seg = []
        total = 0
        max_score = -np.inf
        fetched_bytes = 0
        for si, vals, idx, tot, mx, synced in launched:
            if synced is None:                 # device result: D2H fetch
                seg = self.segments[si]
                try:
                    vals = np.asarray(vals)
                    idx = np.asarray(idx)
                    bad = check_finite(vals)
                except Exception as exc:       # fault surfaced at sync
                    if not is_device_error(exc):
                        raise
                    health.record_failure("dispatch", exc)
                    if not host_capable:
                        raise DeviceDegradedError(
                            "device failure syncing segment "
                            f"[{seg.seg_id}]: "
                            f"{type(exc).__name__}: {exc}") from exc
                    bad = -1                   # recompute below
                if bad:
                    if bad > 0:
                        health.record_poison(
                            kernel="run_topk", segment=seg.seg_id,
                            index=self.index_name, shard=self.shard_id,
                            bad=bad)
                        if not host_capable:
                            raise DeviceDegradedError(
                                "non-finite device scores on segment "
                                f"[{seg.seg_id}] and the plan has no "
                                "host fallback")
                    _ledger().record_host_fallback()
                    vals, idx, tot, mx = plan.host_topk(  # engine-ok: poison-recompute backend
                        bind, seg, self.ctx.lives[id(seg)],
                        min(k_want, seg.n_docs), min_score)
                    vals = np.asarray(vals)
                    idx = np.asarray(idx)
                else:
                    health.record_success("dispatch")
                    fetched_bytes += vals.nbytes + idx.nbytes + 16
            else:
                vals = synced
                idx = np.asarray(idx)
            keep = vals > -np.inf
            per_seg.append((vals[keep], np.full(int(keep.sum()), si, _I32),
                            idx[keep]))
            total += int(tot)
            max_score = max(max_score, float(mx))
        if fetched_bytes:
            _ledger().record_fetch(fetched_bytes,
                                   time.monotonic() - t_sync)
        rows, total, max_score = self._merge_topk(per_seg, k_want, total,
                                                  max_score)
        if prof is not None:
            prof.add("reduce", time.monotonic() - t_red)
        return rows, total, max_score, total_is_lower_bound

    def _topk_host_parallel(self, plan, bind, k_want, min_score,
                            ms_host, iattrs):
        """Host fast path over many segments, scored concurrently on the
        engine threadpool.  Pruning decisions (can-match, min_score
        block-max) run up front on this thread — they are cheap and
        deterministic per segment — then each surviving segment's
        ``host_topk`` runs as one pool task; the merge is the same
        ``_merge_topk`` the sequential path uses, so results are
        byte-identical to a sequential scan."""
        from opensearch_tpu.common.tasks import check_current
        from opensearch_tpu.search.engine import query_engine

        cand = []
        for si, seg in enumerate(self.segments):
            check_current()        # cancellation point per segment
            if not plan.can_match(bind, seg):
                _metrics().counter("search.segments_pruned").inc()
                if iattrs is not None:
                    iattrs["pruned"] += 1
                continue
            if ms_host is not None \
                    and plan.max_score_bound(bind, seg) < ms_host:
                _metrics().counter("search.segments_pruned").inc()
                if iattrs is not None:
                    iattrs["pruned"] += 1
                continue
            cand.append((si, seg))
            if iattrs is not None:
                iattrs["scanned"] += 1
        def score_one(seg):
            with _tracer().start_span(
                    "segment.dispatch",
                    {"segment": seg.seg_id, "index": self.index_name,
                     "shard": self.shard_id}):
                return plan.host_topk(  # engine-ok: host fast-path backend
                    bind, seg, self.ctx.lives[id(seg)],
                    min(k_want, seg.n_docs), min_score)

        outs = query_engine().pool.run_all(
            [(lambda seg=seg: score_one(seg)) for _si, seg in cand])
        per_seg = []
        total = 0
        max_score = -np.inf
        for (si, _seg), (vals, idx, tot, mx) in zip(cand, outs):
            vals = np.asarray(vals)
            idx = np.asarray(idx)
            keep = vals > -np.inf
            per_seg.append((vals[keep],
                            np.full(int(keep.sum()), si, _I32),
                            idx[keep]))
            total += int(tot)
            max_score = max(max_score, float(mx))
        rows, total, max_score = self._merge_topk(per_seg, k_want,
                                                  total, max_score)
        return rows, total, max_score, False

    @staticmethod
    def _harvest_kth(launched, k_want, kth):
        """Update the running k-th best score from programs that ALREADY
        finished — ``is_ready()`` results live on the host, so reading
        them never blocks the dispatch pipeline (the MaxScore running
        threshold, fed at async-dispatch granularity)."""
        ready = []
        for entry in launched:
            if entry[5] is None and getattr(entry[1], "is_ready",
                                            lambda: False)():
                entry[5] = np.asarray(entry[1])      # sync-ok (is_ready)
            if entry[5] is not None:
                ready.append(entry[5])
        if not ready:
            return kth
        vals = np.concatenate(ready).ravel()
        vals = vals[vals > -np.inf]
        if len(vals) < k_want:
            return kth
        cand = float(np.partition(vals, -k_want)[-k_want])  # sync-ok
        return cand if kth is None or cand > kth else kth

    def _topk_from_views(self, views, k_want, prof=None):
        """Top-k out of an already-run full-scores pass (aggs requests)."""
        if prof is not None:
            with prof.phase("reduce"):
                return self._topk_from_views(views, k_want)
        per_seg = []
        total = 0
        max_score = -np.inf
        for si, (seg, dseg, scores, matched) in enumerate(views):
            if k_want == 0:
                total += int(np.asarray(matched).sum())
                continue
            k = min(k_want, dseg.n_pad)
            vals, idx, tot, mx = P.topk_from_scores(scores, k, matched)
            vals = np.asarray(vals)
            idx = np.asarray(idx)
            keep = vals > -np.inf
            per_seg.append((vals[keep], np.full(int(keep.sum()), si, _I32),
                            idx[keep]))
            total += int(tot)
            max_score = max(max_score, float(mx))
        if k_want == 0:
            return [], total, None
        return self._merge_topk(per_seg, k_want, total, max_score)

    def _sort_key_columns(self, seg, spec, scores_np):
        """Per-doc sort key for one segment + one sort clause.  Returns
        (keys ndarray or list, is_numeric)."""
        field, order = spec["field"], spec["order"]
        if field == "_score":
            return scores_np.astype(np.float64), True
        if field == "_doc":
            return np.arange(seg.n_docs, dtype=np.int64), True
        ft = self.mapper.field_type(field)
        if ft is None:
            raise IllegalArgumentError(f"No mapping found for [{field}] in order to sort on")
        if ft.dv_kind in ("long", "double"):
            dv = seg.numeric_dv.get(field)
            if dv is None:
                sentinel = _missing_sentinel(ft.dv_kind, order, spec["missing"])
                return np.full(seg.n_docs, sentinel,
                               np.int64 if ft.dv_kind == "long" else np.float64), True
            keys = (dv.minv if order == "asc" else dv.maxv).copy()
            missing = ~dv.exists
            keys[missing] = _missing_sentinel(ft.dv_kind, order, spec["missing"])
            return keys, True
        if ft.dv_kind == "ordinal":
            dv = seg.ordinal_dv.get(field)
            out = []
            for i in range(seg.n_docs):
                if dv is None or not dv.exists[i]:
                    out.append(None)
                else:
                    o = dv.min_ord[i] if order == "asc" else dv.max_ord[i]
                    out.append(dv.ord_terms[o])
            return out, False
        raise IllegalArgumentError(
            f"sorting on field [{field}] of type [{ft.type_name}] is not supported")

    def _field_sorted(self, plan, bind, needed, k_want, sort_specs, min_score,
                      views=None, row_filter=None, search_after=None,
                      deadline=None, ckey=None, prof=None):
        """``k_want=None`` returns EVERY matched row (scroll
        materialization); ``row_filter(seg_i, local)`` implements sliced
        scans; ``search_after`` drops rows at-or-before the given sort
        tuple (PIT pagination)."""
        rows = []
        total = 0
        _inner = ("can_match", "dispatch", "prepare")
        if prof is not None:
            t_sort = time.monotonic()
            spent0 = sum(prof.phases.get(p, 0.0) for p in _inner)
        if views is None:
            views = self._run_full(plan, bind, needed, min_score,
                                   deadline=deadline, ckey=ckey,
                                   prof=prof)
        for si, (seg, dseg, scores, matched) in enumerate(views):
            matched_np = np.asarray(matched)[: seg.n_docs]
            scores_np = np.asarray(scores)[: seg.n_docs]
            idxs = np.nonzero(matched_np)[0]
            if row_filter is not None and len(idxs):
                keep = np.fromiter((row_filter(si, int(i)) for i in idxs),
                                   bool, count=len(idxs))
                idxs = idxs[keep]
            # total reflects THIS cursor's doc set: a slice reports the
            # slice's count, not the whole match count
            total += len(idxs)
            if len(idxs) == 0:
                continue
            key_cols = [self._sort_key_columns(seg, spec, scores_np)
                        for spec in sort_specs]
            for i in idxs:
                keyvals = []
                for (col, _num), spec in zip(key_cols, sort_specs):
                    keyvals.append(col[int(i)])
                rows.append({"seg": si, "local": int(i), "sort": keyvals,
                             "score": float(scores_np[i])})
        cmp = _sort_comparator(sort_specs)
        rows.sort(key=functools.cmp_to_key(cmp))
        if search_after is not None:
            coerced = []
            for v, spec in zip(search_after, sort_specs):
                ft = (None if spec["field"] == "_score"
                      else self.ctx.field_type(spec["field"]))
                if ft is not None and isinstance(v, str) \
                        and ft.dv_kind in ("long", "double"):
                    # date strings etc. compare in COLUMN space
                    v = ft.range_bound(v)
                coerced.append(v)
            probe = {"sort": coerced, "seg": _I32_MAX,
                     "local": _I32_MAX}
            rows = [r for r in rows if cmp(r, probe) > 0]
        out = []
        nanos_mult = [1_000_000 if (spec["field"] != "_score"
                                    and getattr(self.ctx.field_type(
                                        spec["field"]), "type_name", "")
                                    == "date_nanos") else None
                      for spec in sort_specs]
        for row in rows[:k_want]:
            vals = []
            for v, mult in zip(row["sort"], nanos_mult):
                sv = _sort_value(v)
                # date_nanos sort keys render in NANOS (the reference's
                # resolution-aware sort serialization)
                vals.append(sv * mult if mult and isinstance(
                    sv, int) else sv)
            out.append({"seg": row["seg"], "local": row["local"],
                        "score": None, "sort": vals})
        if prof is not None:
            # host-side key build + comparator sort is the reduce share
            # (segment scan phases were recorded inline by _run_full)
            spent = sum(prof.phases.get(p, 0.0) for p in _inner) - spent0
            prof.add("reduce", max(
                0.0, time.monotonic() - t_sort - spent))
        return out, total, None

    def _rescored(self, rows, rescore):
        """Query rescorer (search/rescore/QueryRescorer): re-rank the top
        window by combining the original score with a rescore query's
        score for those docs; tail rows keep their order."""
        spec = rescore[0] if isinstance(rescore, list) else rescore
        q = spec.get("query") or {}
        window = int(spec.get("window_size", 10))
        rq_json = q.get("rescore_query")
        if rq_json is None:
            raise IllegalArgumentError(
                "[rescore] requires [query.rescore_query]")
        qw = float(q.get("query_weight", 1.0))
        rw = float(q.get("rescore_query_weight", 1.0))
        mode = str(q.get("score_mode", "total"))
        rplan, rbind = self.compiled(rq_json, scored=True)
        rneeded = rplan.arrays()
        # per-segment rescore scores, read only at the window's docs
        seg_scores: dict[int, np.ndarray] = {}
        seg_matched: dict[int, np.ndarray] = {}
        window_rows = rows[:window]
        segs_needed = {r["seg"] for r in window_rows}
        for si, (seg, dseg, scores, matched) in enumerate(
                self._run_full(rplan, rbind, rneeded, None)):
            if si in segs_needed:
                seg_scores[si] = np.asarray(scores)
                seg_matched[si] = np.asarray(matched)
        combine = {"total": lambda a, b: a + b,
                   "multiply": lambda a, b: a * b,
                   "avg": lambda a, b: (a + b) / 2.0,
                   "max": max, "min": min}.get(mode)
        if combine is None:
            raise IllegalArgumentError(
                f"unknown rescore score_mode [{mode}]")
        out = []
        for r in window_rows:
            base = qw * (r.get("score") or 0.0)
            if seg_matched.get(r["seg"]) is not None and \
                    seg_matched[r["seg"]][r["local"]]:
                rs = rw * float(seg_scores[r["seg"]][r["local"]])
                new = combine(base, rs)
            else:
                new = base       # unmatched docs keep the weighted base
            out.append({**r, "score": new})
        out.sort(key=lambda r: (-r["score"], r["seg"], r["local"]))
        out.extend(rows[window:])
        max_score = out[0]["score"] if out else None
        return out, max_score

    def _collapsed(self, plan, bind, needed, k_want, sort_specs,
                   min_score, collapse, views, search_after=None):
        """Field collapsing (search/collapse/): one hit per distinct
        value of the collapse field — the best-ranked in result order."""
        field = collapse.get("field") if isinstance(collapse, dict) \
            else None
        if not field:
            raise IllegalArgumentError("[collapse] requires a [field]")
        ft = self.ctx.field_type(field)
        if ft is None or ft.dv_kind not in ("long", "double", "ordinal"):
            raise IllegalArgumentError(
                f"cannot collapse on [{field}]: keyword or numeric doc "
                "values required")
        if sort_specs is not None:
            ordered, total, _ = self._field_sorted(
                plan, bind, needed, None, sort_specs, min_score, views,
                search_after=search_after)
        elif views is not None:
            # an aggs pass already ran the full query: rank from it
            # instead of a second device execution
            ordered, total = self._rows_from_views(views)
        else:
            ordered, total = self.scan_rows(
                {"query": None, "min_score": min_score}, None,
                _precompiled=(plan, bind, needed))
        seen: set = set()
        out = []
        for r in ordered:
            seg = self.segments[r["seg"]]
            key = self._collapse_key(seg, field, ft, r["local"])
            if key in seen:
                continue
            seen.add(key)
            out.append({**r, "fields": {field: [key]}})
            if len(out) >= k_want:
                break
        max_score = (out[0].get("score") if out and sort_specs is None
                     else None)
        return out, total, max_score

    def _rows_from_views(self, views):
        """All matched rows in (score desc, seg, local) order out of an
        already-run full-scores pass."""
        per_scores, per_ids = [], []
        total = 0
        for si, (seg, dseg, scores, matched) in enumerate(views):
            m = np.asarray(matched)[: seg.n_docs]
            s = np.asarray(scores)[: seg.n_docs]
            idxs = np.nonzero(m)[0]
            total += len(idxs)
            per_scores.append(s[idxs])
            per_ids.append((np.full(len(idxs), si, np.int32), idxs))
        if not per_scores:
            return [], 0
        sc = np.concatenate(per_scores)
        segi = np.concatenate([a for a, _l in per_ids])
        local = np.concatenate([l for _a, l in per_ids])
        order = np.lexsort((local, segi, -sc))
        return [{"seg": int(segi[i]), "local": int(local[i]),
                 "score": float(sc[i])} for i in order], total

    @staticmethod
    def _collapse_key(seg, field, ft, local):
        ndv = seg.numeric_dv.get(field)
        if ndv is not None and ndv.exists[local]:
            v = ndv.minv[local]
            return int(v) if ft.dv_kind == "long" else float(v)
        odv = seg.ordinal_dv.get(field)
        if odv is not None and odv.exists[local] and \
                odv.min_ord[local] >= 0:
            return odv.ord_terms[int(odv.min_ord[local])]
        return None                      # missing values collapse together

    def scan_rows(self, body: Optional[dict] = None, slice_spec=None,
                  _precompiled=None):
        """Materialize EVERY matched row in result order (scroll-context
        creation; SliceBuilder partition via ``slice_spec``).  Returns
        (rows, total) where rows carry seg/local/score/sort."""
        from opensearch_tpu.search.contexts import slice_filter

        body = body or {}
        pred = slice_filter(slice_spec)
        sort_specs = _parse_sort(body.get("sort"))
        min_score = body.get("min_score")
        if _precompiled is not None:
            plan, bind, needed = _precompiled
        else:
            needs_scores = sort_specs is None or min_score is not None \
                or any(s["field"] == "_score" for s in sort_specs)
            plan, bind = self.compiled(body.get("query"),
                                       scored=needs_scores)
            needed = plan.arrays()
        if not self.segments:
            return [], 0
        if sort_specs is not None:
            rows, total, _ = self._field_sorted(
                plan, bind, needed, None, sort_specs, min_score,
                row_filter=pred)
            return rows, total
        per_seg_scores, per_seg_ids = [], []
        total = 0
        for si, (seg, dseg, scores, matched) in enumerate(
                self._run_full(plan, bind, needed, min_score)):
            m = np.asarray(matched)[: seg.n_docs]
            s = np.asarray(scores)[: seg.n_docs]
            idxs = np.nonzero(m)[0]
            if pred is not None and len(idxs):
                keep = np.fromiter((pred(si, int(i)) for i in idxs), bool,
                                   count=len(idxs))
                idxs = idxs[keep]
            total += len(idxs)     # the slice's own count (see above)
            per_seg_scores.append(s[idxs])
            per_seg_ids.append((np.full(len(idxs), si, np.int32), idxs))
        if not per_seg_scores:
            return [], 0
        sc = np.concatenate(per_seg_scores)
        segi = np.concatenate([a for a, _l in per_seg_ids])
        local = np.concatenate([l for _a, l in per_seg_ids])
        order = np.lexsort((local, segi, -sc))
        rows = [{"seg": int(segi[i]), "local": int(local[i]),
                 "score": float(sc[i])} for i in order]
        # full-materialization cost (scroll creation) attributed to the
        # owning task — the rows themselves move to the ScrollContext's
        # own breaker reservation when a context adopts them
        from opensearch_tpu.common.tasks import charge_current
        charge_current(len(rows) * 96, "scan rows")
        return rows, total


def _missing_sentinel(kind, order, missing):
    if missing not in ("_last", "_first"):
        return int(missing) if kind == "long" else float(missing)
    last = missing == "_last"
    if kind == "long":
        big, small = LONG_MISSING_MAX, LONG_MISSING_MIN
    else:
        big, small = np.inf, -np.inf
    if order == "asc":
        return big if last else small
    return small if last else big


def _cmp_values(a, b, order: str, missing: str) -> int:
    if a is None or b is None:
        if a is None and b is None:
            return 0
        none_first = (missing == "_first")
        if a is None:
            return -1 if none_first else 1
        return 1 if none_first else -1
    if a == b:
        return 0
    lt = a < b
    if order == "desc":
        lt = not lt
    return -1 if lt else 1


def _sort_comparator(specs):
    def cmp(r1, r2):
        for i, spec in enumerate(specs):
            c = _cmp_values(r1["sort"][i], r2["sort"][i], spec["order"],
                           spec["missing"])
            if c:
                return c
        if r1["seg"] != r2["seg"]:
            return -1 if r1["seg"] < r2["seg"] else 1
        return -1 if r1["local"] < r2["local"] else (0 if r1["local"] == r2["local"] else 1)
    return cmp


def merge_hit_rows(rows, sort_json):
    """Coordinator-side merge of per-source sorted hit lists — the
    SearchPhaseController.sortDocs analog shared by the cluster
    scatter-gather and the REST multi-index merge.

    ``rows``: list of ``(hit, source_ordinal, position)`` where hits from
    each source arrive already sorted and position is the hit's rank
    within its source.  Without a sort clause, merges by
    (score desc, source, position); with one, merges by the hits' sort
    keys with (source, position) as the tie-break.  Returns hits in
    merged order.
    """
    import functools

    specs = _parse_sort(sort_json)
    if specs is None:
        rows = sorted(rows, key=lambda t: (-(t[0]["_score"] or 0.0),
                                           t[1], t[2]))
    else:
        cmp = _sort_comparator(specs)
        rows = sorted(rows, key=functools.cmp_to_key(
            lambda a, b: cmp({"sort": a[0].get("sort", []),
                              "seg": a[1], "local": a[2]},
                             {"sort": b[0].get("sort", []),
                              "seg": b[1], "local": b[2]})))
    return [h for h, _s, _p in rows]


def _sort_value(v):
    if v is None:
        return None
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v
