"""Query insights: always-on workload attribution for every search.

Analog of the reference's query-insights plugin (top-N query collection
with latency/cpu/memory rankings) extended with what ROADMAP item 1
actually needs before a continuous batcher can be built or tuned:
per-plan-signature workload statistics.  PR 9's profiler answers "why
was THIS query slow" when a client opts in with ``profile:true``;
this service answers "what is the FLEET doing" for every completed
search/msearch member at negligible cost:

- which canonical plan signatures (the PR-5 ``compiled``-cache key)
  dominate, how often they arrive, and what they cost (latency
  percentiles, task CPU/heap),
- how they executed (host/device/batched/mesh path, plan-cache and
  request-cache hit/miss, segments pruned vs scanned),
- how COALESCABLE the workload is: the fraction of arrivals landing
  within a configurable Δt of the previous arrival of the same
  signature — exactly the sizing input a continuous batcher keyed by
  plan signature needs (GPUSparse-style batch-parallel traversal only
  pays off when concurrent arrivals actually share shapes).

Wiring: execution layers *emit* lightweight records through a
contextvar sink (``collecting()`` installed by the edge that owns the
request — the REST dispatcher, the cluster data-node query-phase
handler, or the bench harness); the edge enriches them (X-Opaque-Id,
task CPU/heap, outcome) and feeds ``QueryInsightsService.record``.
Responses are NEVER mutated, so search responses are byte-identical
with insights enabled or disabled (pinned in tests/test_insights.py).

Bounded + breaker-accounted: the top-N ring and the per-signature
rollup table charge the ``request`` breaker and self-evict under
pressure (the common/cache.py discipline), so insights can stay
always-on without becoming its own memory incident.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import threading
import time
from collections import deque
from typing import Optional

from opensearch_tpu.common.telemetry import Histogram

# -- emission channel ------------------------------------------------------
#
# The executor runs under whatever edge installed a sink; with no sink
# installed (plain library use) emission is a contextvar read + a None
# check — effectively free, and nothing is retained.

_sink: "contextvars.ContextVar[Optional[list]]" = \
    contextvars.ContextVar("opensearch_tpu_insight_sink", default=None)


def emit(**fields) -> Optional[dict]:
    """Append one insight record to the ambient sink (no-op without
    one).  Returns the record so the emitter may keep annotating it."""
    sink = _sink.get()
    if sink is None:
        return None
    sink.append(fields)
    return fields


def annotate_last(**fields) -> None:
    """Merge fields into the most recently emitted record (used by
    layers above the executor — e.g. the request-cache admission point
    knows hit/miss, the executor does not)."""
    sink = _sink.get()
    if sink:
        sink[-1].update(fields)


@contextlib.contextmanager
def collecting():
    """Install a fresh sink for one request scope; yields the list the
    execution layers emit into."""
    sink: list = []
    token = _sink.set(sink)
    try:
        yield sink
    finally:
        _sink.reset(token)


@contextlib.contextmanager
def suppressed():
    """Mask the ambient sink (inner scatter legs of a search that
    already emits its own single record — the mesh/host fallback
    scatter — must not double-count arrivals)."""
    token = _sink.set(None)
    try:
        yield
    finally:
        _sink.reset(token)


# -- signatures ------------------------------------------------------------

def canonical_query(query_json) -> Optional[str]:
    """The PR-5 plan-cache canonicalization: key order in the body never
    changes the signature.  None for unserializable bodies."""
    try:
        return json.dumps(query_json, sort_keys=True,
                          separators=(",", ":"))
    except (TypeError, ValueError):
        return None


def scored_for_body(body: dict) -> bool:
    """Mirror of the executor's needs_scores derivation (sort without
    _score skips BM25 scoring) so a coordinator computes the SAME plan
    signature a data node stamps (parity pinned in tests)."""
    sort = body.get("sort")
    if sort is None:
        return True
    specs = sort if isinstance(sort, list) else [sort]
    for s in specs:
        field = s if isinstance(s, str) else next(iter(s), None) \
            if isinstance(s, dict) else None
        if field == "_score":
            return True
    return body.get("min_score") is not None


def signature_hash(canonical: Optional[str], scored: bool = True) -> str:
    """Short stable id for a (canonical query, scored) plan key — THE
    bounded label value the Prometheus exposition is allowed to use."""
    if canonical is None:
        return "_unsigned"
    h = hashlib.sha1(
        (canonical + ("|s" if scored else "|u")).encode()).hexdigest()
    return h[:12]


# -- per-signature rollup --------------------------------------------------

_SOURCE_CHARS = 160          # operator-readable source excerpt
_CLIENT_SLOTS = 8            # top X-Opaque-Id values kept per signature


class _SignatureRollup:
    """Aggregate workload statistics for ONE plan signature."""

    __slots__ = ("signature", "source", "scored", "count", "first_ts",
                 "last_ts", "hist", "inter_sum", "inter_min", "inter_n",
                 "coalesced", "paths", "outcomes", "plan_cache_hits",
                 "request_cache_hits", "request_cache_total", "pruned",
                 "scanned", "cpu_nanos", "heap_peak", "clients",
                 "batched_members", "transfer_bytes",
                 "batch_size_sum", "batch_size_max",
                 "queue_wait_ms_sum", "queue_wait_ms_max",
                 "queue_waits")

    def __init__(self, signature: str, source: str, scored: bool,
                 now: float):
        self.signature = signature
        self.source = source
        self.scored = scored
        self.count = 0
        self.first_ts = now
        self.last_ts: Optional[float] = None
        self.hist = Histogram(signature)     # fixed buckets, tiny
        self.inter_sum = 0.0
        self.inter_min: Optional[float] = None
        self.inter_n = 0
        self.coalesced = 0
        self.paths: dict[str, int] = {}
        self.outcomes: dict[str, int] = {}
        self.plan_cache_hits = 0
        self.request_cache_hits = 0
        self.request_cache_total = 0
        self.pruned = 0
        self.scanned = 0
        self.cpu_nanos = 0
        self.heap_peak = 0
        self.clients: dict[str, int] = {}
        self.batched_members = 0
        # host↔device bytes (stage + fetch-back) the device ledger
        # attributed to this signature's executions
        self.transfer_bytes = 0
        # continuous-batcher attribution: realized group sizes of the
        # members this signature contributed, and the queue wait they
        # paid parking for the shared dispatch (search/engine.py)
        self.batch_size_sum = 0
        self.batch_size_max = 0
        self.queue_wait_ms_sum = 0.0
        self.queue_wait_ms_max = 0.0
        self.queue_waits = 0

    def add(self, rec: dict, now: float, coalesce_window_s: float) -> None:
        self.count += 1
        self.hist.observe(float(rec.get("took_ms", 0.0)))
        if self.last_ts is not None:
            delta = max(0.0, now - self.last_ts)
            self.inter_sum += delta
            self.inter_n += 1
            if self.inter_min is None or delta < self.inter_min:
                self.inter_min = delta
            if delta <= coalesce_window_s:
                self.coalesced += 1
        self.last_ts = now
        path = str(rec.get("execution_path") or "unknown")
        self.paths[path] = self.paths.get(path, 0) + 1
        outcome = str(rec.get("outcome") or "ok")
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        if rec.get("plan_cache") == "hit":
            self.plan_cache_hits += 1
        rc = rec.get("request_cache")
        if rc in ("hit", "miss"):
            self.request_cache_total += 1
            if rc == "hit":
                self.request_cache_hits += 1
        self.pruned += int(rec.get("pruned") or 0)
        self.scanned += int(rec.get("scanned") or 0)
        self.transfer_bytes += int(rec.get("transfer_bytes") or 0)
        self.cpu_nanos += int(rec.get("cpu_nanos") or 0)
        self.heap_peak = max(self.heap_peak,
                             int(rec.get("heap_bytes") or 0))
        if rec.get("batched"):
            self.batched_members += 1
            size = int(rec["batched"])
            self.batch_size_sum += size
            if size > self.batch_size_max:
                self.batch_size_max = size
        qw = rec.get("queue_wait_ms")
        if qw is not None:
            qw = float(qw)
            self.queue_waits += 1
            self.queue_wait_ms_sum += qw
            if qw > self.queue_wait_ms_max:
                self.queue_wait_ms_max = qw
        opaque = rec.get("opaque_id")
        if opaque:
            opaque = str(opaque)[:64]
            if opaque in self.clients or len(self.clients) < _CLIENT_SLOTS:
                self.clients[opaque] = self.clients.get(opaque, 0) + 1

    def coalescable_fraction(self) -> float:
        """Fraction of this signature's arrivals that landed within the
        coalesce window of the previous arrival — the continuous
        batcher's per-shape sizing input."""
        return self.coalesced / self.count if self.count else 0.0

    def to_dict(self) -> dict:
        h = self.hist.stats()
        out = {
            "signature": self.signature,
            "source": self.source,
            "scored": self.scored,
            "count": self.count,
            "latency_ms": {
                "avg": h.get("avg_in_millis", 0.0),
                "max": h.get("max_in_millis", 0.0),
                "p50": h.get("percentiles", {}).get("50.0", 0.0),
                "p90": h.get("percentiles", {}).get("90.0", 0.0),
                "p99": h.get("percentiles", {}).get("99.0", 0.0),
            },
            "coalesced": self.coalesced,
            "coalescable_fraction": round(
                self.coalescable_fraction(), 4),
            "execution_paths": dict(self.paths),
            "outcomes": dict(self.outcomes),
            "plan_cache_hits": self.plan_cache_hits,
            "segments": {"pruned": self.pruned, "scanned": self.scanned},
            "cpu_time_in_nanos": self.cpu_nanos,
            "peak_heap_in_bytes": self.heap_peak,
            "batched_members": self.batched_members,
            "device_transfer_bytes": self.transfer_bytes,
        }
        if self.batched_members:
            out["batched_group_size"] = {
                "mean": round(self.batch_size_sum
                              / self.batched_members, 3),
                "max": self.batch_size_max,
            }
        if self.queue_waits:
            out["queue_wait_ms"] = {
                "mean": round(self.queue_wait_ms_sum
                              / self.queue_waits, 3),
                "max": round(self.queue_wait_ms_max, 3),
            }
        if self.request_cache_total:
            out["request_cache"] = {
                "hits": self.request_cache_hits,
                "total": self.request_cache_total}
        if self.inter_n:
            out["interarrival_ms"] = {
                "mean": round(self.inter_sum / self.inter_n * 1000, 3),
                "min": round((self.inter_min or 0.0) * 1000, 3)}
        if self.clients:
            out["clients"] = dict(sorted(
                self.clients.items(), key=lambda kv: (-kv[1], kv[0])))
        return out


# -- per-tenant rollup -----------------------------------------------------

_TENANT_SIG_SLOTS = 8        # top plan signatures kept per tenant


class _TenantRollup:
    """Aggregate workload statistics for ONE tenant (X-Opaque-Id) —
    the attribution half of per-tenant QoS: who is sending what, how
    much it costs, and how often it was degraded (429/shed/partial)."""

    __slots__ = ("tenant", "count", "rejected", "took_sum", "took_max",
                 "cpu_nanos", "outcomes", "signatures", "first_ts",
                 "last_ts")

    def __init__(self, tenant: str, now: float):
        self.tenant = tenant
        self.count = 0
        self.rejected = 0          # admission 429s (no plan existed)
        self.took_sum = 0.0
        self.took_max = 0.0
        self.cpu_nanos = 0
        self.outcomes: dict[str, int] = {}
        self.signatures: dict[str, int] = {}
        self.first_ts = now
        self.last_ts = now

    def add(self, sig: str, took_ms: float, cpu_nanos: int,
            outcome: str, now: float) -> None:
        self.count += 1
        self.took_sum += took_ms
        if took_ms > self.took_max:
            self.took_max = took_ms
        self.cpu_nanos += cpu_nanos
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        if sig in self.signatures \
                or len(self.signatures) < _TENANT_SIG_SLOTS:
            self.signatures[sig] = self.signatures.get(sig, 0) + 1
        self.last_ts = now

    def to_dict(self) -> dict:
        return {
            "tenant": self.tenant,
            "count": self.count,
            "rejected": self.rejected,
            "latency_ms": {
                "avg": round(self.took_sum / self.count, 3)
                if self.count else 0.0,
                "max": round(self.took_max, 3),
            },
            "cpu_time_in_nanos": self.cpu_nanos,
            "outcomes": dict(self.outcomes),
            "top_signatures": dict(sorted(
                self.signatures.items(),
                key=lambda kv: (-kv[1], kv[0]))),
        }


# -- the service -----------------------------------------------------------

_RECORD_OVERHEAD_BYTES = 400        # per-record bookkeeping estimate
_ROLLUP_OVERHEAD_BYTES = 1200       # per-rollup (histogram + dicts)
_TENANT_OVERHEAD_BYTES = 600        # per-tenant rollup (small dicts)


class QueryInsightsService:
    """Always-on bounded recorder: a sliding-window top-N ring (ranked
    by latency, task CPU, or task heap at read time) plus per-signature
    rollups with latency percentiles, interarrival statistics, and the
    coalescability report.  Injectable clock for deterministic tests;
    ``request``-breaker accounted with self-evict-then-drop under
    pressure."""

    def __init__(self, *, node_id: str = "", top_n: int = 10,
                 window_s: float = 300.0,
                 coalesce_window_ms: float = 10.0,
                 ring_capacity: int = 256, max_signatures: int = 128,
                 max_tenants: int = 64,
                 clock=time.monotonic, breaker: str = "request"):
        self.node_id = node_id
        self.enabled = True
        self.top_n = int(top_n)
        self.window_s = float(window_s)
        self.coalesce_window_ms = float(coalesce_window_ms)
        self.ring_capacity = int(ring_capacity)
        self.max_signatures = int(max_signatures)
        self.max_tenants = int(max_tenants)
        self.clock = clock
        self._breaker_name = breaker
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque()
        self._rollups: dict[str, _SignatureRollup] = {}
        self._tenants: dict[str, _TenantRollup] = {}
        self._outcomes: dict[str, int] = {}
        self._ring_bytes = 0
        self._total = 0
        self._coalesced_total = 0
        self._dropped = 0
        self._rejected = 0
        self._evictions = 0

    # -- settings (dynamic, _cluster/settings consumers) -------------------

    def set_enabled(self, v: bool) -> None:
        self.enabled = bool(v)

    def set_top_n(self, n: int) -> None:
        self.top_n = max(1, int(n))

    def set_window_s(self, s: float) -> None:
        self.window_s = max(1.0, float(s))

    def set_coalesce_window_ms(self, ms: float) -> None:
        self.coalesce_window_ms = max(0.0, float(ms))

    # -- breaker plumbing --------------------------------------------------

    def _breaker(self):
        from opensearch_tpu.common.breakers import breaker_service
        return getattr(breaker_service(), self._breaker_name, None)

    def _charge(self, n: int) -> bool:
        """True when the reservation landed; on pressure, evict the
        oldest ring entries once and retry (cache.py's self-evict-then-
        skip), else the caller drops the record."""
        from opensearch_tpu.common.breakers import CircuitBreakingError
        breaker = self._breaker()
        if breaker is None:
            return True
        try:
            breaker.add_estimate(n, label="query_insights")
            return True
        except CircuitBreakingError:
            self._evict_oldest(max(1, len(self._ring) // 4))
            try:
                breaker.add_estimate(n, label="query_insights")
                return True
            except CircuitBreakingError:
                return False

    def _release(self, n: int) -> None:
        breaker = self._breaker()
        if breaker is not None:
            breaker.release(n)

    def _evict_oldest(self, k: int) -> None:
        for _ in range(min(k, len(self._ring))):
            old = self._ring.popleft()
            freed = old.get("_bytes", _RECORD_OVERHEAD_BYTES)
            self._ring_bytes -= freed
            self._release(freed)
            self._evictions += 1

    # -- recording ---------------------------------------------------------

    def record(self, rec: dict, *, opaque_id: Optional[str] = None,
               cpu_nanos: Optional[int] = None,
               heap_bytes: Optional[int] = None,
               outcome: Optional[str] = None) -> None:
        """Ingest one completed search (or msearch member).  ``rec`` is
        an ``emit()`` record: signature (canonical query string or
        None), scored, took_ms, execution_path, plan_cache,
        request_cache, index, pruned, scanned, batched, timed_out."""
        if not self.enabled:
            return
        canonical = rec.get("signature")
        scored = bool(rec.get("scored", True))
        sig = signature_hash(canonical, scored)
        if opaque_id is not None:
            rec.setdefault("opaque_id", opaque_id)
        if cpu_nanos is not None:
            rec["cpu_nanos"] = int(cpu_nanos)
        if heap_bytes is not None:
            rec["heap_bytes"] = int(heap_bytes)
        if outcome is not None:
            rec["outcome"] = outcome
        elif "outcome" not in rec:
            rec["outcome"] = ("timeout" if rec.get("timed_out")
                              else "ok")
        now = self.clock()
        source = (canonical or "<unserializable>")[:_SOURCE_CHARS]
        entry = {
            "signature": sig,
            "source": source,
            "ts": now,
            "took_ms": float(rec.get("took_ms", 0.0)),
            "cpu_nanos": int(rec.get("cpu_nanos") or 0),
            "heap_bytes": int(rec.get("heap_bytes") or 0),
            "execution_path": rec.get("execution_path") or "unknown",
            "plan_cache": rec.get("plan_cache") or "miss",
            "request_cache": rec.get("request_cache") or "none",
            "outcome": rec["outcome"],
            "node": self.node_id,
        }
        if rec.get("index"):
            entry["index"] = rec["index"]
        if rec.get("opaque_id"):
            entry["x_opaque_id"] = str(rec["opaque_id"])[:64]
        if rec.get("batched"):
            entry["batched"] = int(rec["batched"])
        cost = _RECORD_OVERHEAD_BYTES + len(source)
        entry["_bytes"] = cost
        with self._lock:
            if not self._charge(cost):
                self._dropped += 1
                return
            self._ring.append(entry)
            self._ring_bytes += cost
            if len(self._ring) > self.ring_capacity:
                self._evict_oldest(len(self._ring) - self.ring_capacity)
            self._expire(now)
            roll = self._rollups.pop(sig, None)
            if roll is None:
                if not self._charge(_ROLLUP_OVERHEAD_BYTES):
                    self._dropped += 1
                    return
                if len(self._rollups) >= self.max_signatures:
                    # dict insertion order IS the recency order (every
                    # touch below reinserts), so the head is the LRU
                    # victim — O(1), no scan on the hot path
                    victim = next(iter(self._rollups))
                    del self._rollups[victim]
                    self._release(_ROLLUP_OVERHEAD_BYTES)
                    self._evictions += 1
                roll = _SignatureRollup(sig, source, scored, now)
            self._rollups[sig] = roll          # move-to-end on touch
            was_coalesced = roll.coalesced
            roll.add(rec, now, self.coalesce_window_ms / 1000.0)
            self._total += 1
            self._coalesced_total += roll.coalesced - was_coalesced
            self._outcomes[rec["outcome"]] = \
                self._outcomes.get(rec["outcome"], 0) + 1
            tenant = self._tenant_locked(rec.get("opaque_id"), now)
            if tenant is not None:
                tenant.add(sig, float(rec.get("took_ms", 0.0)),
                           int(rec.get("cpu_nanos") or 0),
                           rec["outcome"], now)

    def _tenant_locked(self, opaque_id, now: float):
        """The tenant rollup for this record's X-Opaque-Id (the
        anonymous default pool for unlabeled traffic) — same bounded
        LRU + breaker discipline as the signature rollups.  Caller
        holds the lock; None when the breaker refused the charge."""
        from opensearch_tpu.search.qos import tenant_label
        label = tenant_label(opaque_id)
        roll = self._tenants.pop(label, None)
        if roll is None:
            if not self._charge(_TENANT_OVERHEAD_BYTES):
                return None
            if len(self._tenants) >= self.max_tenants:
                victim = next(iter(self._tenants))
                del self._tenants[victim]
                self._release(_TENANT_OVERHEAD_BYTES)
                self._evictions += 1
            roll = _TenantRollup(label, now)
        self._tenants[label] = roll            # move-to-end on touch
        return roll

    def record_rejected(self, opaque_id: Optional[str] = None) -> None:
        """An admission-gate 429 happened before any plan existed —
        counted (the shed load is workload evidence too) but never a
        ring entry; attributed to the rejected client's tenant."""
        with self._lock:
            self._rejected += 1
            tenant = self._tenant_locked(opaque_id, self.clock())
            if tenant is not None:
                tenant.rejected += 1

    def _expire(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._ring and self._ring[0]["ts"] < cutoff:
            self._evict_oldest(1)

    # -- readout -----------------------------------------------------------

    _RANKS = {"latency": "took_ms", "cpu": "cpu_nanos",
              "heap": "heap_bytes"}

    def top(self, by: str = "latency", n: Optional[int] = None,
            window_s: Optional[float] = None) -> list[dict]:
        """Top-N records in the sliding window ranked by latency / task
        CPU / task heap, newest-first within ties (deterministic)."""
        key = self._RANKS.get(by)
        if key is None:
            from opensearch_tpu.common.errors import IllegalArgumentError
            raise IllegalArgumentError(
                f"unknown top_queries ranking [{by}]; one of "
                f"{sorted(self._RANKS)}")
        n = self.top_n if n is None else max(1, int(n))
        cutoff = self.clock() - (window_s if window_s is not None
                                 else self.window_s)
        with self._lock:
            live = [dict(r) for r in self._ring if r["ts"] >= cutoff]
        for r in live:
            r.pop("_bytes", None)
        live.sort(key=lambda r: (-r[key], -r["ts"], r["signature"]))
        return live[:n]

    def coalescability(self) -> dict:
        """The batcher sizing report: overall fraction of arrivals that
        landed within Δt of the previous same-signature arrival, plus
        the most coalescable signatures."""
        with self._lock:
            total = self._total
            coalesced = self._coalesced_total
            rolls = list(self._rollups.values())
        best = sorted(
            (r for r in rolls if r.count >= 2),
            key=lambda r: (-r.coalescable_fraction(), -r.count,
                           r.signature))[:5]
        return {
            "window_ms": self.coalesce_window_ms,
            "arrivals": total,
            "coalesced": coalesced,
            "coalescable_fraction": round(coalesced / total, 4)
            if total else 0.0,
            "top_signatures": [
                {"signature": r.signature,
                 "count": r.count,
                 "coalescable_fraction": round(
                     r.coalescable_fraction(), 4)}
                for r in best],
        }

    def tenants(self) -> dict:
        """Per-tenant rollups keyed by tenant label (the QoS
        attribution surface: ``?by=tenant``, ``_nodes/stats``, the
        noisy-neighbor soak's evidence)."""
        with self._lock:
            return {label: r.to_dict()
                    for label, r in sorted(self._tenants.items())}

    def tenant_totals(self) -> dict:
        """Compact per-tenant (count, rejected) — the QoS controller's
        cheap per-tick signal."""
        with self._lock:
            return {label: {"count": r.count, "rejected": r.rejected}
                    for label, r in self._tenants.items()}

    def section(self, by: str = "latency",
                n: Optional[int] = None) -> dict:
        """The full per-node insights section (`_insights/top_queries`
        fan-in unit and the flight-recorder snapshot).  ``by=tenant``
        serves the same section with the latency top ranking — the
        per-tenant rollups are always included; any other unknown
        ranking still rejects (400) inside ``top``."""
        rank_by = "latency" if by == "tenant" else by
        with self._lock:
            rollups = {sig: r.to_dict()
                       for sig, r in sorted(self._rollups.items())}
        return {
            "node": self.node_id,
            "enabled": self.enabled,
            "window_s": self.window_s,
            "top_queries": self.top(by=rank_by, n=n),
            "signatures": rollups,
            "tenants": self.tenants(),
            "coalescability": self.coalescability(),
            "totals": self.stats(),
        }

    def stats(self) -> dict:
        """Compact `_nodes/stats` ``query_insights`` block."""
        with self._lock:
            total = self._total
            coalesced = self._coalesced_total
            return {
                "enabled": self.enabled,
                "records": total,
                "ring_size": len(self._ring),
                "ring_bytes": self._ring_bytes,
                "signatures": len(self._rollups),
                "tenants": len(self._tenants),
                "outcomes": dict(self._outcomes),
                "coalesced": coalesced,
                "coalescable_fraction": round(coalesced / total, 4)
                if total else 0.0,
                "rejected": self._rejected,
                "dropped": self._dropped,
                "evictions": self._evictions,
            }

    # -- Prometheus exposition ---------------------------------------------

    @staticmethod
    def _label_value(v: str) -> str:
        """Prometheus label-value escaping.  Every value flowing through
        here is a 12-hex signature hash or a node id — bounded by
        construction (ring/rollup caps), never raw request data; the
        label-cardinality lint (tools/check_prom_labels.py) enforces
        that discipline repo-wide."""
        return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    def prometheus_text(self) -> str:
        """Labeled exposition for the top signatures by count: the
        signature is always a LABEL, never part of the metric name, so
        the metric-name lint's bounded-name invariant holds and
        dashboards can aggregate across signatures."""
        with self._lock:
            rolls = sorted(self._rollups.values(),
                           key=lambda r: (-r.count, r.signature))
            rolls = rolls[: self.top_n]
            node = self._label_value(self.node_id)
        lines = [
            "# HELP opensearch_tpu_insights_signature_queries_total "
            "Completed searches per plan signature",
            "# TYPE opensearch_tpu_insights_signature_queries_total "
            "counter",
        ]
        for r in rolls:
            sig = self._label_value(r.signature)
            lines.append(
                f'opensearch_tpu_insights_signature_queries_total'
                f'{{signature="{sig}",node="{node}"}} {r.count}')  # label-ok: signature hashes via the bounded top-N path
        lines.append(
            "# HELP opensearch_tpu_insights_signature_latency_p99_ms "
            "p99 latency per plan signature (milliseconds)")
        lines.append(
            "# TYPE opensearch_tpu_insights_signature_latency_p99_ms "
            "gauge")
        for r in rolls:
            sig = self._label_value(r.signature)
            p99 = r.hist.percentile(99)
            lines.append(
                f'opensearch_tpu_insights_signature_latency_p99_ms'
                f'{{signature="{sig}",node="{node}"}} {p99:.6g}')  # label-ok: signature hashes via the bounded top-N path
        lines.append(
            "# HELP opensearch_tpu_insights_signature_coalescable_ratio "
            "Fraction of arrivals within the coalesce window")
        lines.append(
            "# TYPE opensearch_tpu_insights_signature_coalescable_ratio "
            "gauge")
        for r in rolls:
            sig = self._label_value(r.signature)
            frac = r.coalescable_fraction()
            lines.append(
                f'opensearch_tpu_insights_signature_coalescable_ratio'
                f'{{signature="{sig}",node="{node}"}} {frac:.6g}')  # label-ok: signature hashes via the bounded top-N path
        # per-tenant attribution: tenant is a LABEL from the bounded
        # (max_tenants, then top-N-by-count) rollup table, never a name
        with self._lock:
            trolls = sorted(self._tenants.values(),
                            key=lambda r: (-r.count, r.tenant))
            trolls = trolls[: self.top_n]
        lines.append(
            "# HELP opensearch_tpu_insights_tenant_queries_total "
            "Completed searches per tenant (X-Opaque-Id)")
        lines.append(
            "# TYPE opensearch_tpu_insights_tenant_queries_total "
            "counter")
        for r in trolls:
            ten = self._label_value(r.tenant)
            lines.append(
                f'opensearch_tpu_insights_tenant_queries_total'
                f'{{tenant="{ten}",node="{node}"}} {r.count}')  # label-ok: bounded tenant rollup slots via the top-N path
        lines.append(
            "# HELP opensearch_tpu_insights_tenant_rejected_total "
            "Admission 429s per tenant (X-Opaque-Id)")
        lines.append(
            "# TYPE opensearch_tpu_insights_tenant_rejected_total "
            "counter")
        for r in trolls:
            ten = self._label_value(r.tenant)
            lines.append(
                f'opensearch_tpu_insights_tenant_rejected_total'
                f'{{tenant="{ten}",node="{node}"}} {r.rejected}')  # label-ok: bounded tenant rollup slots via the top-N path
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._evict_oldest(len(self._ring))
            for _ in range(len(self._rollups)):
                self._release(_ROLLUP_OVERHEAD_BYTES)
            self._rollups.clear()
            for _ in range(len(self._tenants)):
                self._release(_TENANT_OVERHEAD_BYTES)
            self._tenants.clear()
            self._outcomes.clear()
            self._total = self._coalesced_total = 0
            self._dropped = self._rejected = self._evictions = 0


# -- cluster fan-in merge --------------------------------------------------

def merge_sections(sections: dict[str, dict], *, by: str = "latency",
                   n: int = 10) -> dict:
    """Coordinator-side merge of per-node insights sections into one
    cluster view, provenance-annotated like PR 9's profile merge: every
    merged top entry keeps the node that recorded it, every merged
    signature lists its per-node contributions, and unreachable nodes
    are reported as errors instead of silently dropped.  Deterministic:
    stable sort keys everywhere (rank metric desc, then node asc, then
    signature asc)."""
    rank_key = QueryInsightsService._RANKS.get(by, "took_ms")
    merged_top: list[dict] = []
    merged_sigs: dict[str, dict] = {}
    merged_tenants: dict[str, dict] = {}
    errors: dict[str, str] = {}
    total = coalesced = 0
    for node in sorted(sections):
        sec = sections[node]
        if not isinstance(sec, dict) or "error" in sec:
            errors[node] = (sec or {}).get("error", "unreachable") \
                if isinstance(sec, dict) else "unreachable"
            continue
        for entry in sec.get("top_queries", []):
            entry = dict(entry)
            entry.setdefault("node", node)
            merged_top.append(entry)
        tot = sec.get("totals", {})
        total += int(tot.get("records", 0))
        coalesced += int(tot.get("coalesced", 0))
        for sig, roll in (sec.get("signatures") or {}).items():
            m = merged_sigs.get(sig)
            if m is None:
                m = {"signature": sig, "source": roll.get("source"),
                     "count": 0, "coalesced": 0, "nodes": {}}
                merged_sigs[sig] = m
            m["count"] += int(roll.get("count", 0))
            m["coalesced"] += int(roll.get("coalesced", 0))
            m["nodes"][node] = roll
        for tenant, roll in (sec.get("tenants") or {}).items():
            m = merged_tenants.get(tenant)
            if m is None:
                m = {"tenant": tenant, "count": 0, "rejected": 0,
                     "cpu_time_in_nanos": 0, "outcomes": {},
                     "nodes": {}}
                merged_tenants[tenant] = m
            m["count"] += int(roll.get("count", 0))
            m["rejected"] += int(roll.get("rejected", 0))
            m["cpu_time_in_nanos"] += int(
                roll.get("cpu_time_in_nanos", 0))
            for outcome, c in (roll.get("outcomes") or {}).items():
                m["outcomes"][outcome] = \
                    m["outcomes"].get(outcome, 0) + int(c)
            m["nodes"][node] = roll
    for m in merged_sigs.values():
        m["coalescable_fraction"] = round(
            m["coalesced"] / m["count"], 4) if m["count"] else 0.0
    merged_top.sort(key=lambda r: (-float(r.get(rank_key, 0.0)),
                                   str(r.get("node", "")),
                                   str(r.get("signature", ""))))
    out = {
        "top_queries": merged_top[: max(1, int(n))],
        "signatures": dict(sorted(merged_sigs.items())),
        "tenants": dict(sorted(merged_tenants.items())),
        "coalescability": {
            "arrivals": total,
            "coalesced": coalesced,
            "coalescable_fraction": round(coalesced / total, 4)
            if total else 0.0,
        },
        "nodes": {node: sec for node, sec in sorted(sections.items())
                  if isinstance(sec, dict) and "error" not in sec},
    }
    if errors:
        out["failed_nodes"] = errors
    return out
