"""Search backpressure: node duress detection, runaway-query
cancellation, and admission control.

Analog of the reference's ``search.backpressure`` subsystem (ref
search/backpressure/SearchBackpressureService.java,
SearchBackpressureSettings, trackers/NodeDuressTrackers.java,
trackers/TaskResourceUsageTrackers.java): a periodic monitor decides the
node is *in duress* (circuit-breaker pressure, search thread-pool queue
depth, CPU load — each behind an injectable probe so tests drive it
deterministically) and, once the duress persists for
``num_successive_breaches`` evaluations, picks the most
resource-consuming cancellable search tasks and cancels them —
rate-limited by a token bucket so a storm of small queries is not mass
cancelled (``cancellation_burst``/``cancellation_rate``).  In
``monitor_only`` mode eligible tasks are only counted; ``disabled``
turns the whole loop off.  ``SearchAdmissionController`` is the edge
half: a concurrent-search permit gate that rejects with 429 +
``Retry-After`` *before* work queues unboundedly (the reference's
admission control at the RestController/coordinator boundary).

Everything observable lands in ``stats()`` → ``_nodes/stats``
``search_backpressure``.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Optional

from opensearch_tpu.common.errors import OpenSearchTpuError

MODES = ("disabled", "monitor_only", "enforced")

#: task actions the backpressure service may cancel (search family only:
#: writes and admin tasks are never sacrificed to search overload)
SEARCH_ACTION_PREFIXES = ("indices:data/read/search",
                          "indices:data/read/msearch",
                          "indices:data/read/scroll")


class SearchRejectedError(OpenSearchTpuError):
    """Admission-control rejection: the node is saturated and queueing
    would only grow the backlog.  429 + Retry-After, like the
    reference's OpenSearchRejectedExecutionException mapping."""
    status = 429
    retry_after_seconds = 1


def _is_search_task(task) -> bool:
    return any(task.action.startswith(p) for p in SEARCH_ACTION_PREFIXES)


class TokenBucket:
    """Deterministic rate limiter on an injectable monotonic clock (ref
    search/backpressure/stats/../TokenBucket.java)."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()

    def request(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class DuressTracker:
    """One node-duress signal: probe() -> current value, breached when
    value >= threshold.  Probes are plain callables so tests inject
    synthetic load (ref trackers/NodeDuressTrackers.NodeDuressTracker)."""

    def __init__(self, name: str, probe: Callable[[], float],
                 threshold: float):
        self.name = name
        self.probe = probe
        self.threshold = float(threshold)
        self.breach_count = 0

    def check(self) -> bool:
        try:
            value = float(self.probe())
        except Exception:  # noqa: BLE001 — a broken probe is "no duress"
            value = 0.0
        self.last_value = value
        if value >= self.threshold:
            self.breach_count += 1
            return True
        return False

    def stats(self) -> dict:
        return {"threshold": self.threshold,
                "current": getattr(self, "last_value", 0.0),
                "breach_count": self.breach_count}


def _breaker_pressure() -> float:
    """Parent-breaker utilization in [0, 1] — the heap-usage stand-in
    (device/host budgets are what this engine actually runs out of)."""
    from opensearch_tpu.common.breakers import breaker_service
    svc = breaker_service()
    used = sum(b.used for b in svc.parent._children)
    return used / svc.parent.limit if svc.parent.limit else 0.0


def _default_cpu_load() -> float:
    """1-minute load average per core; 0.0 where unsupported."""
    import os
    try:
        return os.getloadavg()[0] / (os.cpu_count() or 1)
    except (OSError, AttributeError):
        return 0.0


#: bounded per-tenant accounting: the stats table never grows past this
#: many labels (labels beyond the cap fold into the default pool's row)
_TENANT_STAT_SLOTS = 64

#: Retry-After bounds around the measured drain estimate
RETRY_AFTER_FLOOR_S = 1
RETRY_AFTER_CEILING_S = 30

#: EWMA smoothing for the permit-release interval (drain rate)
_RELEASE_ALPHA = 0.3


class SearchAdmissionController:
    """Concurrent-search permit gate at the REST/coordinator edge: a
    request either gets a permit immediately or is rejected with 429 —
    never queued (the reference rejects from the search thread pool's
    bounded queue; this gate fails faster and with Retry-After).

    Multi-tenant: when ``search.qos.tenant_shares`` names tenants, the
    global budget is carved into weighted per-tenant pools keyed by the
    client's X-Opaque-Id (unlabeled traffic shares a default pool), so
    one flooding tenant exhausts its OWN share and 429s while everyone
    else's permits stay available.  The QoS controller can additionally
    squeeze a noisy tenant's carved share via ``tenant_penalty`` — the
    effective pool never drops below one permit (isolation, never
    starvation).  With no shares configured the gate is the legacy
    single pool; per-tenant accounting still records who used it.

    ``Retry-After`` on rejections is derived from the measured drain
    rate: an EWMA of the permit-release interval, clamped to
    [``RETRY_AFTER_FLOOR_S``, ``RETRY_AFTER_CEILING_S``] — a fast gate
    says "1", a wedged one tells clients to actually back off."""

    def __init__(self, service: "SearchBackpressureService",
                 max_concurrent: int = 256):
        self._service = service
        self.max_concurrent = int(max_concurrent)
        self._inflight = 0
        self.rejected_count = 0
        # coordinator-side duress sheds draw from the SAME budget as
        # edge 429s: one client-visible-rejection ledger, one occupancy
        # signal (ROADMAP item 4's unified overload budget)
        self.shed_count = 0
        # per-tenant QoS: configured weights, controller-set penalties,
        # live per-pool inflight, and the bounded accounting table
        self.tenant_shares: dict = {}
        self.default_share = 1.0
        self.tenant_penalty: dict = {}
        self._tenant_inflight: dict = {}
        self._tenant_stats: dict = {}
        # measured drain rate: EWMA of seconds between permit releases
        self._release_interval_ewma: "float | None" = None
        self._last_release: "float | None" = None
        self._lock = threading.Lock()

    # -- tenant plumbing (search.qos.* consumers) --------------------------

    def set_tenant_shares(self, shares: dict) -> None:
        with self._lock:
            self.tenant_shares = dict(shares or {})

    def set_default_share(self, share: float) -> None:
        with self._lock:
            self.default_share = max(0.0, float(share))

    def set_tenant_penalty(self, label: str, penalty: float) -> None:
        """QoS-controller seam: squeeze (or restore) one tenant's
        carved share.  A penalty of 1.0 clears the entry."""
        with self._lock:
            if penalty >= 1.0:
                self.tenant_penalty.pop(label, None)
            else:
                self.tenant_penalty[label] = float(penalty)

    def _pool_label(self, tenant) -> str:
        from opensearch_tpu.search.qos import DEFAULT_POOL, tenant_label
        label = tenant_label(tenant)
        if label != DEFAULT_POOL and label not in self.tenant_shares \
                and len(self._tenant_stats) >= _TENANT_STAT_SLOTS \
                and label not in self._tenant_stats:
            return DEFAULT_POOL     # bounded table: overflow folds in
        return label

    def _tenant_limit_locked(self, label: str) -> "int | None":
        """The carved permit cap for one pool; None = no carving (no
        shares configured).  Caller holds the lock."""
        if not self.tenant_shares:
            return None
        from opensearch_tpu.search.qos import DEFAULT_POOL
        total = sum(self.tenant_shares.values()) + self.default_share
        weight = (self.tenant_shares.get(label, self.default_share)
                  if label != DEFAULT_POOL else self.default_share)
        if total <= 0:
            return self.max_concurrent
        cap = max(1, int(self.max_concurrent * weight / total))
        penalty = self.tenant_penalty.get(label)
        if penalty is not None:
            cap = max(1, int(cap * penalty))
        return cap

    def _tenant_stat_locked(self, label: str) -> dict:
        st = self._tenant_stats.get(label)
        if st is None:
            st = self._tenant_stats[label] = {
                "admitted": 0, "rejected": 0, "shed": 0}
        return st

    def shed_priority(self, tenant) -> float:
        """Tenant-weighted shed bias for the coordinator duress path:
        a penalized (noisy) tenant's requests shed at proportionally
        lower admission occupancy than everyone else's."""
        with self._lock:
            label = self._pool_label(tenant)
            return float(self.tenant_penalty.get(label, 1.0))

    def cancellation_bias(self, opaque_id) -> float:
        """Tenant weighting for backpressure victim election: tasks of
        low-share (or penalized) tenants rank as proportionally bigger
        resource consumers, so the noisy neighbor's runaway query is
        cancelled before a premium tenant's equal-cost one.  1.0 when
        no shares are configured (legacy election order)."""
        with self._lock:
            if not self.tenant_shares:
                return 1.0
            label = self._pool_label(opaque_id)
            from opensearch_tpu.search.qos import DEFAULT_POOL
            weight = (self.tenant_shares.get(label, self.default_share)
                      if label != DEFAULT_POOL else self.default_share)
            penalty = self.tenant_penalty.get(label, 1.0)
            return (self.default_share / max(weight, 1e-9)) \
                / max(penalty, 1e-9)

    # -- drain-rate Retry-After --------------------------------------------

    def _retry_after_locked(self) -> int:
        ewma = self._release_interval_ewma
        if ewma is None:
            return RETRY_AFTER_FLOOR_S
        import math
        return min(RETRY_AFTER_CEILING_S,
                   max(RETRY_AFTER_FLOOR_S, math.ceil(ewma)))

    def retry_after_hint(self) -> int:
        """Seconds until a permit plausibly frees, from the measured
        permit-release EWMA (floor/ceiling clamped) — the Retry-After
        every 429 on this node ships."""
        with self._lock:
            return self._retry_after_locked()

    def occupancy(self) -> float:
        """Permit-gate utilization in [0, 1] — the shared overload
        signal coordinator shed decisions consult."""
        with self._lock:
            if self.max_concurrent <= 0:
                return 1.0
            return self._inflight / self.max_concurrent

    def record_shed(self, n: int = 1, tenant=None) -> None:
        """A coordinator-side duress shed counted against this gate's
        rejection budget (429s and sheds are the same client-visible
        degradation, so they share one ledger), attributed to the
        tenant whose request was shed."""
        with self._lock:
            self.shed_count += int(n)
            self._tenant_stat_locked(
                self._pool_label(tenant))["shed"] += int(n)

    @contextlib.contextmanager
    def acquire(self, kind: str = "search", tenant=None):
        self._service.maybe_tick()
        with self._lock:
            label = self._pool_label(tenant)
            reason = None
            if self._inflight >= self.max_concurrent:
                reason = (f"too many concurrent searches "
                          f"[{self._inflight}] >= "
                          f"[{self.max_concurrent}]")
            elif (self._service.mode == "enforced"
                    and self._service.in_duress()):
                reason = "node is in duress"
            else:
                cap = self._tenant_limit_locked(label)
                if cap is not None \
                        and self._tenant_inflight.get(label, 0) >= cap:
                    reason = (f"tenant [{label}] is over its admission "
                              f"share [{self._tenant_inflight[label]}]"
                              f" >= [{cap}]")
            if reason is not None:
                self.rejected_count += 1
                self._tenant_stat_locked(label)["rejected"] += 1
                err = SearchRejectedError(
                    f"rejected execution of [{kind}]: {reason}; reduce "
                    "concurrency or retry after the Retry-After interval")
                err.retry_after_seconds = self._retry_after_locked()
                raise err
            self._inflight += 1
            self._tenant_inflight[label] = \
                self._tenant_inflight.get(label, 0) + 1
            self._tenant_stat_locked(label)["admitted"] += 1
        try:
            yield
        finally:
            with self._lock:
                self._inflight -= 1
                left = self._tenant_inflight.get(label, 1) - 1
                if left <= 0:
                    self._tenant_inflight.pop(label, None)
                else:
                    self._tenant_inflight[label] = left
                # measured drain rate: every release is one sample of
                # "how fast do permits come back"
                now = self._service._clock()
                if self._last_release is not None:
                    sample = max(0.0, now - self._last_release)
                    if self._release_interval_ewma is None:
                        self._release_interval_ewma = sample
                    else:
                        self._release_interval_ewma = (
                            _RELEASE_ALPHA * sample
                            + (1.0 - _RELEASE_ALPHA)
                            * self._release_interval_ewma)
                self._last_release = now

    def tenant_stats(self) -> dict:
        """Per-tenant budget accounting (the ``tenants`` block of the
        admission stats): carved cap, live inflight, admitted /
        rejected / shed tallies, and any controller penalty."""
        with self._lock:
            out = {}
            for label in sorted(self._tenant_stats):
                st = dict(self._tenant_stats[label])
                st["inflight"] = self._tenant_inflight.get(label, 0)
                cap = self._tenant_limit_locked(label)
                if cap is not None:
                    st["max_concurrent"] = cap
                penalty = self.tenant_penalty.get(label)
                if penalty is not None:
                    st["penalty"] = penalty
                out[label] = st
            return out

    def stats(self) -> dict:
        tenants = self.tenant_stats()
        with self._lock:
            occupancy = (self._inflight / self.max_concurrent
                         if self.max_concurrent > 0 else 1.0)
            return {"current": self._inflight,
                    "max_concurrent": self.max_concurrent,
                    "occupancy": round(occupancy, 4),
                    "rejected_count": self.rejected_count,
                    "shed_count": self.shed_count,
                    "rejected_total": self.rejected_count + self.shed_count,
                    "retry_after_s": self._retry_after_locked(),
                    "tenants": tenants}


class SearchBackpressureService:
    """The monitor half.  ``run_once()`` is one deterministic evaluation
    tick; production paces it via ``maybe_tick()`` on the admission path
    and (optionally) ``start_monitor()``'s background thread."""

    def __init__(self, task_manager, thread_pool=None, *,
                 mode: str = "monitor_only",
                 clock: Callable[[], float] = time.monotonic,
                 cpu_load_fn: Optional[Callable[[], float]] = None,
                 cpu_threshold: float = 0.9,
                 heap_threshold: float = 0.85,
                 queue_threshold: int = 500,
                 num_successive_breaches: int = 3,
                 cancellation_rate: float = 1.0,
                 cancellation_burst: float = 10.0,
                 max_cancellations_per_tick: int = 1,
                 max_concurrent_searches: int = 256,
                 interval_s: float = 1.0,
                 task_cpu_nanos_threshold: int = int(15e9),
                 task_heap_bytes_threshold: int = 64 << 20,
                 task_elapsed_nanos_threshold: int = int(30e9)):
        self.task_manager = task_manager
        self.thread_pool = thread_pool
        self._mode = mode
        self._clock = clock
        self.interval_s = float(interval_s)
        self.num_successive_breaches = int(num_successive_breaches)
        self.max_cancellations_per_tick = int(max_cancellations_per_tick)
        self.task_cpu_nanos_threshold = int(task_cpu_nanos_threshold)
        self.task_heap_bytes_threshold = int(task_heap_bytes_threshold)
        self.task_elapsed_nanos_threshold = int(task_elapsed_nanos_threshold)
        self._bucket = TokenBucket(cancellation_rate, cancellation_burst,
                                   clock)
        self.trackers = {
            "heap_usage": DuressTracker("heap_usage", _breaker_pressure,
                                        heap_threshold),
            "search_queue": DuressTracker(
                "search_queue", self._search_queue_depth, queue_threshold),
            "cpu_usage": DuressTracker(
                "cpu_usage", cpu_load_fn or _default_cpu_load,
                cpu_threshold),
        }
        self._lock = threading.Lock()
        self._streak = 0
        self._forced_duress = 0        # testing seam (fault injection)
        self._last_tick = None
        self.cancellation_count = 0
        self.monitor_only_count = 0
        self.limit_reached_count = 0
        self._tracker_cancellations = {"cpu_usage": 0, "heap_usage": 0,
                                       "elapsed_time": 0}
        self.admission = SearchAdmissionController(
            self, max_concurrent=max_concurrent_searches)
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # -- settings (dynamic _cluster/settings consumers land here) ---------

    @property
    def mode(self) -> str:
        return self._mode

    def set_mode(self, mode: str) -> None:
        if mode not in MODES:
            raise OpenSearchTpuError(
                f"Invalid SearchBackpressureMode: {mode}")
        self._mode = mode

    def set_max_concurrent_searches(self, n: int) -> None:
        self.admission.max_concurrent = int(n)

    def set_cpu_threshold(self, v: float) -> None:
        self.trackers["cpu_usage"].threshold = float(v)

    def set_heap_threshold(self, v: float) -> None:
        self.trackers["heap_usage"].threshold = float(v)

    def set_queue_threshold(self, v: int) -> None:
        self.trackers["search_queue"].threshold = float(v)

    def set_num_successive_breaches(self, v: int) -> None:
        self.num_successive_breaches = int(v)

    # -- duress evaluation -------------------------------------------------

    def _search_queue_depth(self) -> float:
        if self.thread_pool is None:
            return 0.0
        try:
            return float(self.thread_pool.executor("search").stats()["queue"])
        except OpenSearchTpuError:
            return 0.0

    def force_duress(self, ticks: int = 1) -> None:
        """Deterministic duress simulation: the next ``ticks``
        evaluations read as in-duress regardless of the real probes
        (used by testing/fault_injection.py)."""
        with self._lock:
            self._forced_duress = int(ticks)

    def in_duress(self) -> bool:
        """Did the breach streak reach the configured threshold?"""
        with self._lock:
            return self._streak >= self.num_successive_breaches

    def maybe_tick(self) -> None:
        """Run at most one evaluation per ``interval_s`` — the pacing the
        admission path gives the monitor without a dedicated thread."""
        now = self._clock()
        with self._lock:
            if (self._last_tick is not None
                    and now - self._last_tick < self.interval_s):
                return
            self._last_tick = now
        self.run_once()

    def run_once(self) -> dict:
        """One monitor evaluation: update duress streak; under sustained
        duress rank the cancellable search tasks by resource usage and
        act per mode.  Returns what happened (for tests/logs)."""
        if self._mode == "disabled":
            return {"duress": False, "cancelled": []}
        with self._lock:
            if self._forced_duress > 0:
                self._forced_duress -= 1
                breached = True
            else:
                breached = False
        if not breached:
            breached = any(t.check() for t in self.trackers.values())
        with self._lock:
            self._streak = self._streak + 1 if breached else 0
            if self._streak < self.num_successive_breaches:
                return {"duress": False, "cancelled": []}
        victims = self._eligible_tasks()
        cancelled = []
        for task, dominant in victims[: self.max_cancellations_per_tick]:
            if self._mode == "monitor_only":
                with self._lock:
                    self.monitor_only_count += 1
                continue
            if not self._bucket.request():
                with self._lock:
                    self.limit_reached_count += 1
                continue
            task.cancel(
                "cancelled by search backpressure: node under duress, "
                f"task exceeded [{dominant}] threshold "
                f"(cpu={task.cpu_time_nanos}ns, "
                f"heap={task.heap_bytes}b)")
            with self._lock:
                self.cancellation_count += 1
                self._tracker_cancellations[dominant] += 1
            cancelled.append(task)
        from opensearch_tpu.common.telemetry import metrics
        if cancelled:
            metrics().counter("search_backpressure.cancellations").inc(
                len(cancelled))
        return {"duress": True, "cancelled": cancelled}

    def _eligible_tasks(self) -> list:
        """(task, dominant-tracker) pairs over every cancellable,
        not-yet-cancelled search task exceeding a per-task resource
        threshold, most expensive first (the reference's
        TaskResourceUsageTrackers election).  With tenant shares
        configured the overshoot is tenant-weighted: a low-share or
        QoS-penalized tenant's task ranks as a proportionally bigger
        consumer, so the noisy neighbor's runaway query is sacrificed
        before a premium tenant's equal-cost one."""
        out = []
        for t in self.task_manager.list():
            if not t.cancellable or t.cancelled or not _is_search_task(t):
                continue
            cpu, heap, elapsed = (t.cpu_time_nanos, t.heap_bytes,
                                  t.elapsed_nanos)
            over = []
            if cpu >= self.task_cpu_nanos_threshold:
                over.append(("cpu_usage", cpu / self.task_cpu_nanos_threshold))
            if heap >= self.task_heap_bytes_threshold:
                over.append(("heap_usage",
                             heap / self.task_heap_bytes_threshold))
            if elapsed >= self.task_elapsed_nanos_threshold:
                over.append(("elapsed_time",
                             elapsed / self.task_elapsed_nanos_threshold))
            if not over:
                continue
            # dominant tracker = largest relative overshoot; rank tasks
            # by that same measure so "the top resource consumer" is
            # well defined and deterministic
            dominant, score = max(over, key=lambda kv: kv[1])
            bias = self.admission.cancellation_bias(
                getattr(t, "headers", {}).get("X-Opaque-Id"))
            out.append((score * bias, t.id, t, dominant))
        out.sort(key=lambda e: (-e[0], e[1]))
        return [(t, dominant) for _s, _id, t, dominant in out]

    # -- background monitor (optional; tests drive run_once directly) -----

    def start_monitor(self) -> None:
        if self._monitor is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.run_once()
                except Exception:  # noqa: BLE001 — monitor must survive
                    pass
        self._monitor = threading.Thread(
            target=loop, name="search-backpressure-monitor", daemon=True)
        self._monitor.start()

    def stop_monitor(self) -> None:
        # bounded join: teardown must return even if a tick is wedged in
        # a probe — the thread is a daemon, so a missed join can't block
        # process exit either
        monitor, self._monitor = self._monitor, None
        if monitor is not None:
            self._stop.set()
            monitor.join(timeout=5)

    def monitor_alive(self) -> bool:
        monitor = self._monitor
        return monitor is not None and monitor.is_alive()

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        # admission stats gather BEFORE taking the service lock: the
        # admission gate's acquire() path holds its own lock while it
        # consults in_duress() (service lock) — taking the locks in the
        # opposite order here would deadlock
        admission_stats = self.admission.stats()
        monitor_alive = self.monitor_alive()
        with self._lock:
            return {
                "mode": self._mode,
                "monitor": {"running": monitor_alive,
                            "interval_s": self.interval_s},
                "cancellation_count": self.cancellation_count,
                "monitor_only_count": self.monitor_only_count,
                "limit_reached_count": self.limit_reached_count,
                "node_duress": {
                    "streak": self._streak,
                    "in_duress": (self._streak
                                  >= self.num_successive_breaches),
                    "trackers": {name: t.stats()
                                 for name, t in self.trackers.items()},
                },
                "search_task": {
                    "resource_tracker_cancellations":
                        dict(self._tracker_cancellations),
                    "thresholds": {
                        "cpu_time_nanos": self.task_cpu_nanos_threshold,
                        "heap_bytes": self.task_heap_bytes_threshold,
                        "elapsed_time_nanos":
                            self.task_elapsed_nanos_threshold,
                    },
                },
                "admission_control": admission_stats,
            }
