"""Search backpressure: node duress detection, runaway-query
cancellation, and admission control.

Analog of the reference's ``search.backpressure`` subsystem (ref
search/backpressure/SearchBackpressureService.java,
SearchBackpressureSettings, trackers/NodeDuressTrackers.java,
trackers/TaskResourceUsageTrackers.java): a periodic monitor decides the
node is *in duress* (circuit-breaker pressure, search thread-pool queue
depth, CPU load — each behind an injectable probe so tests drive it
deterministically) and, once the duress persists for
``num_successive_breaches`` evaluations, picks the most
resource-consuming cancellable search tasks and cancels them —
rate-limited by a token bucket so a storm of small queries is not mass
cancelled (``cancellation_burst``/``cancellation_rate``).  In
``monitor_only`` mode eligible tasks are only counted; ``disabled``
turns the whole loop off.  ``SearchAdmissionController`` is the edge
half: a concurrent-search permit gate that rejects with 429 +
``Retry-After`` *before* work queues unboundedly (the reference's
admission control at the RestController/coordinator boundary).

Everything observable lands in ``stats()`` → ``_nodes/stats``
``search_backpressure``.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Optional

from opensearch_tpu.common.errors import OpenSearchTpuError

MODES = ("disabled", "monitor_only", "enforced")

#: task actions the backpressure service may cancel (search family only:
#: writes and admin tasks are never sacrificed to search overload)
SEARCH_ACTION_PREFIXES = ("indices:data/read/search",
                          "indices:data/read/msearch",
                          "indices:data/read/scroll")


class SearchRejectedError(OpenSearchTpuError):
    """Admission-control rejection: the node is saturated and queueing
    would only grow the backlog.  429 + Retry-After, like the
    reference's OpenSearchRejectedExecutionException mapping."""
    status = 429
    retry_after_seconds = 1


def _is_search_task(task) -> bool:
    return any(task.action.startswith(p) for p in SEARCH_ACTION_PREFIXES)


class TokenBucket:
    """Deterministic rate limiter on an injectable monotonic clock (ref
    search/backpressure/stats/../TokenBucket.java)."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()

    def request(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class DuressTracker:
    """One node-duress signal: probe() -> current value, breached when
    value >= threshold.  Probes are plain callables so tests inject
    synthetic load (ref trackers/NodeDuressTrackers.NodeDuressTracker)."""

    def __init__(self, name: str, probe: Callable[[], float],
                 threshold: float):
        self.name = name
        self.probe = probe
        self.threshold = float(threshold)
        self.breach_count = 0

    def check(self) -> bool:
        try:
            value = float(self.probe())
        except Exception:  # noqa: BLE001 — a broken probe is "no duress"
            value = 0.0
        self.last_value = value
        if value >= self.threshold:
            self.breach_count += 1
            return True
        return False

    def stats(self) -> dict:
        return {"threshold": self.threshold,
                "current": getattr(self, "last_value", 0.0),
                "breach_count": self.breach_count}


def _breaker_pressure() -> float:
    """Parent-breaker utilization in [0, 1] — the heap-usage stand-in
    (device/host budgets are what this engine actually runs out of)."""
    from opensearch_tpu.common.breakers import breaker_service
    svc = breaker_service()
    used = sum(b.used for b in svc.parent._children)
    return used / svc.parent.limit if svc.parent.limit else 0.0


def _default_cpu_load() -> float:
    """1-minute load average per core; 0.0 where unsupported."""
    import os
    try:
        return os.getloadavg()[0] / (os.cpu_count() or 1)
    except (OSError, AttributeError):
        return 0.0


class SearchAdmissionController:
    """Concurrent-search permit gate at the REST/coordinator edge: a
    request either gets a permit immediately or is rejected with 429 —
    never queued (the reference rejects from the search thread pool's
    bounded queue; this gate fails faster and with Retry-After)."""

    def __init__(self, service: "SearchBackpressureService",
                 max_concurrent: int = 256):
        self._service = service
        self.max_concurrent = int(max_concurrent)
        self._inflight = 0
        self.rejected_count = 0
        # coordinator-side duress sheds draw from the SAME budget as
        # edge 429s: one client-visible-rejection ledger, one occupancy
        # signal (ROADMAP item 4's unified overload budget)
        self.shed_count = 0
        self._lock = threading.Lock()

    def occupancy(self) -> float:
        """Permit-gate utilization in [0, 1] — the shared overload
        signal coordinator shed decisions consult."""
        with self._lock:
            if self.max_concurrent <= 0:
                return 1.0
            return self._inflight / self.max_concurrent

    def record_shed(self, n: int = 1) -> None:
        """A coordinator-side duress shed counted against this gate's
        rejection budget (429s and sheds are the same client-visible
        degradation, so they share one ledger)."""
        with self._lock:
            self.shed_count += int(n)

    @contextlib.contextmanager
    def acquire(self, kind: str = "search"):
        self._service.maybe_tick()
        with self._lock:
            reason = None
            if self._inflight >= self.max_concurrent:
                reason = (f"too many concurrent searches "
                          f"[{self._inflight}] >= "
                          f"[{self.max_concurrent}]")
            elif (self._service.mode == "enforced"
                    and self._service.in_duress()):
                reason = "node is in duress"
            if reason is not None:
                self.rejected_count += 1
                raise SearchRejectedError(
                    f"rejected execution of [{kind}]: {reason}; reduce "
                    "concurrency or retry after the Retry-After interval")
            self._inflight += 1
        try:
            yield
        finally:
            with self._lock:
                self._inflight -= 1

    def stats(self) -> dict:
        with self._lock:
            occupancy = (self._inflight / self.max_concurrent
                         if self.max_concurrent > 0 else 1.0)
            return {"current": self._inflight,
                    "max_concurrent": self.max_concurrent,
                    "occupancy": round(occupancy, 4),
                    "rejected_count": self.rejected_count,
                    "shed_count": self.shed_count,
                    "rejected_total": self.rejected_count + self.shed_count}


class SearchBackpressureService:
    """The monitor half.  ``run_once()`` is one deterministic evaluation
    tick; production paces it via ``maybe_tick()`` on the admission path
    and (optionally) ``start_monitor()``'s background thread."""

    def __init__(self, task_manager, thread_pool=None, *,
                 mode: str = "monitor_only",
                 clock: Callable[[], float] = time.monotonic,
                 cpu_load_fn: Optional[Callable[[], float]] = None,
                 cpu_threshold: float = 0.9,
                 heap_threshold: float = 0.85,
                 queue_threshold: int = 500,
                 num_successive_breaches: int = 3,
                 cancellation_rate: float = 1.0,
                 cancellation_burst: float = 10.0,
                 max_cancellations_per_tick: int = 1,
                 max_concurrent_searches: int = 256,
                 interval_s: float = 1.0,
                 task_cpu_nanos_threshold: int = int(15e9),
                 task_heap_bytes_threshold: int = 64 << 20,
                 task_elapsed_nanos_threshold: int = int(30e9)):
        self.task_manager = task_manager
        self.thread_pool = thread_pool
        self._mode = mode
        self._clock = clock
        self.interval_s = float(interval_s)
        self.num_successive_breaches = int(num_successive_breaches)
        self.max_cancellations_per_tick = int(max_cancellations_per_tick)
        self.task_cpu_nanos_threshold = int(task_cpu_nanos_threshold)
        self.task_heap_bytes_threshold = int(task_heap_bytes_threshold)
        self.task_elapsed_nanos_threshold = int(task_elapsed_nanos_threshold)
        self._bucket = TokenBucket(cancellation_rate, cancellation_burst,
                                   clock)
        self.trackers = {
            "heap_usage": DuressTracker("heap_usage", _breaker_pressure,
                                        heap_threshold),
            "search_queue": DuressTracker(
                "search_queue", self._search_queue_depth, queue_threshold),
            "cpu_usage": DuressTracker(
                "cpu_usage", cpu_load_fn or _default_cpu_load,
                cpu_threshold),
        }
        self._lock = threading.Lock()
        self._streak = 0
        self._forced_duress = 0        # testing seam (fault injection)
        self._last_tick = None
        self.cancellation_count = 0
        self.monitor_only_count = 0
        self.limit_reached_count = 0
        self._tracker_cancellations = {"cpu_usage": 0, "heap_usage": 0,
                                       "elapsed_time": 0}
        self.admission = SearchAdmissionController(
            self, max_concurrent=max_concurrent_searches)
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # -- settings (dynamic _cluster/settings consumers land here) ---------

    @property
    def mode(self) -> str:
        return self._mode

    def set_mode(self, mode: str) -> None:
        if mode not in MODES:
            raise OpenSearchTpuError(
                f"Invalid SearchBackpressureMode: {mode}")
        self._mode = mode

    def set_max_concurrent_searches(self, n: int) -> None:
        self.admission.max_concurrent = int(n)

    def set_cpu_threshold(self, v: float) -> None:
        self.trackers["cpu_usage"].threshold = float(v)

    def set_heap_threshold(self, v: float) -> None:
        self.trackers["heap_usage"].threshold = float(v)

    def set_queue_threshold(self, v: int) -> None:
        self.trackers["search_queue"].threshold = float(v)

    def set_num_successive_breaches(self, v: int) -> None:
        self.num_successive_breaches = int(v)

    # -- duress evaluation -------------------------------------------------

    def _search_queue_depth(self) -> float:
        if self.thread_pool is None:
            return 0.0
        try:
            return float(self.thread_pool.executor("search").stats()["queue"])
        except OpenSearchTpuError:
            return 0.0

    def force_duress(self, ticks: int = 1) -> None:
        """Deterministic duress simulation: the next ``ticks``
        evaluations read as in-duress regardless of the real probes
        (used by testing/fault_injection.py)."""
        with self._lock:
            self._forced_duress = int(ticks)

    def in_duress(self) -> bool:
        """Did the breach streak reach the configured threshold?"""
        with self._lock:
            return self._streak >= self.num_successive_breaches

    def maybe_tick(self) -> None:
        """Run at most one evaluation per ``interval_s`` — the pacing the
        admission path gives the monitor without a dedicated thread."""
        now = self._clock()
        with self._lock:
            if (self._last_tick is not None
                    and now - self._last_tick < self.interval_s):
                return
            self._last_tick = now
        self.run_once()

    def run_once(self) -> dict:
        """One monitor evaluation: update duress streak; under sustained
        duress rank the cancellable search tasks by resource usage and
        act per mode.  Returns what happened (for tests/logs)."""
        if self._mode == "disabled":
            return {"duress": False, "cancelled": []}
        with self._lock:
            if self._forced_duress > 0:
                self._forced_duress -= 1
                breached = True
            else:
                breached = False
        if not breached:
            breached = any(t.check() for t in self.trackers.values())
        with self._lock:
            self._streak = self._streak + 1 if breached else 0
            if self._streak < self.num_successive_breaches:
                return {"duress": False, "cancelled": []}
        victims = self._eligible_tasks()
        cancelled = []
        for task, dominant in victims[: self.max_cancellations_per_tick]:
            if self._mode == "monitor_only":
                with self._lock:
                    self.monitor_only_count += 1
                continue
            if not self._bucket.request():
                with self._lock:
                    self.limit_reached_count += 1
                continue
            task.cancel(
                "cancelled by search backpressure: node under duress, "
                f"task exceeded [{dominant}] threshold "
                f"(cpu={task.cpu_time_nanos}ns, "
                f"heap={task.heap_bytes}b)")
            with self._lock:
                self.cancellation_count += 1
                self._tracker_cancellations[dominant] += 1
            cancelled.append(task)
        from opensearch_tpu.common.telemetry import metrics
        if cancelled:
            metrics().counter("search_backpressure.cancellations").inc(
                len(cancelled))
        return {"duress": True, "cancelled": cancelled}

    def _eligible_tasks(self) -> list:
        """(task, dominant-tracker) pairs over every cancellable,
        not-yet-cancelled search task exceeding a per-task resource
        threshold, most expensive first (the reference's
        TaskResourceUsageTrackers election)."""
        out = []
        for t in self.task_manager.list():
            if not t.cancellable or t.cancelled or not _is_search_task(t):
                continue
            cpu, heap, elapsed = (t.cpu_time_nanos, t.heap_bytes,
                                  t.elapsed_nanos)
            over = []
            if cpu >= self.task_cpu_nanos_threshold:
                over.append(("cpu_usage", cpu / self.task_cpu_nanos_threshold))
            if heap >= self.task_heap_bytes_threshold:
                over.append(("heap_usage",
                             heap / self.task_heap_bytes_threshold))
            if elapsed >= self.task_elapsed_nanos_threshold:
                over.append(("elapsed_time",
                             elapsed / self.task_elapsed_nanos_threshold))
            if not over:
                continue
            # dominant tracker = largest relative overshoot; rank tasks
            # by that same measure so "the top resource consumer" is
            # well defined and deterministic
            dominant, score = max(over, key=lambda kv: kv[1])
            out.append((score, t.id, t, dominant))
        out.sort(key=lambda e: (-e[0], e[1]))
        return [(t, dominant) for _s, _id, t, dominant in out]

    # -- background monitor (optional; tests drive run_once directly) -----

    def start_monitor(self) -> None:
        if self._monitor is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.run_once()
                except Exception:  # noqa: BLE001 — monitor must survive
                    pass
        self._monitor = threading.Thread(
            target=loop, name="search-backpressure-monitor", daemon=True)
        self._monitor.start()

    def stop_monitor(self) -> None:
        # bounded join: teardown must return even if a tick is wedged in
        # a probe — the thread is a daemon, so a missed join can't block
        # process exit either
        monitor, self._monitor = self._monitor, None
        if monitor is not None:
            self._stop.set()
            monitor.join(timeout=5)

    def monitor_alive(self) -> bool:
        monitor = self._monitor
        return monitor is not None and monitor.is_alive()

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        # admission stats gather BEFORE taking the service lock: the
        # admission gate's acquire() path holds its own lock while it
        # consults in_duress() (service lock) — taking the locks in the
        # opposite order here would deadlock
        admission_stats = self.admission.stats()
        monitor_alive = self.monitor_alive()
        with self._lock:
            return {
                "mode": self._mode,
                "monitor": {"running": monitor_alive,
                            "interval_s": self.interval_s},
                "cancellation_count": self.cancellation_count,
                "monitor_only_count": self.monitor_only_count,
                "limit_reached_count": self.limit_reached_count,
                "node_duress": {
                    "streak": self._streak,
                    "in_duress": (self._streak
                                  >= self.num_successive_breaches),
                    "trackers": {name: t.stats()
                                 for name, t in self.trackers.items()},
                },
                "search_task": {
                    "resource_tracker_cancellations":
                        dict(self._tracker_cancellations),
                    "thresholds": {
                        "cpu_time_nanos": self.task_cpu_nanos_threshold,
                        "heap_bytes": self.task_heap_bytes_threshold,
                        "elapsed_time_nanos":
                            self.task_elapsed_nanos_threshold,
                    },
                },
                "admission_control": admission_stats,
            }
