"""One query engine: the single scoring entry every caller routes through.

Before this module, four execution paths coexisted and were wired
separately at each call site: the sequential per-query path
(``ShardSearcher.search``), the msearch-batched kernel
(``search/batch.py``), the CPU host fast path (``ops/bm25.py
HOST_SCORING``) and the 8-device mesh (``parallel/dist_search.py``).
Only clients that happened to speak ``_msearch`` reached the batched
kernel; independent REST requests each paid their own XLA dispatch even
when the insights coalescability report said most zipf-head arrivals
land within a coalesce window of an identical-signature predecessor.

Now ``QueryEngine`` is the one entry (``IndexService.search/msearch``,
the cluster data-node query phase, and the mesh router all call it) and
the kernels are backend decisions inside the one lowering pipeline
(parse -> plan cache -> prepare -> kernel choice); the tier-1 lint
``tools/check_execution_paths.py`` keeps it that way — scoring kernels
may only be invoked from the engine's sanctioned lowering sites.

On top of the unified entry sit the two serving-scale pieces:

- ``ContinuousBatcher`` — inference-serving-style continuous batching
  at the REST edge: concurrent in-flight single searches whose plans
  share a batch group (same field / k family) park for a Δt window
  sized from the measured workload (``search.insights
  .coalesce_window_ms`` — the PR-10 coalescability report's knob) and
  execute as ONE ``batch_impact_union_topk`` dispatch, each caller
  receiving its own response with byte-identical hits.  Non-batchable
  bodies bypass with zero added latency, and a request only ever waits
  when concurrent batchable traffic is actually in flight — serial
  traffic never parks.  Parked members keep holding their REST-edge
  admission permits (the gate wraps the whole handler), so batcher
  occupancy is charged to the existing admission budget and the queue
  cannot become an unbounded buffer under overload; an internal
  ``max_parked`` bound additionally spills late arrivals to the
  sequential path instead of queueing.

- ``SearchThreadpool`` — a bounded pool of explicitly named daemon
  workers that parallelizes the single-threaded host fast path across
  cores for non-coalescable traffic (msearch fallback bodies, the
  per-segment host scoring loop).  Overflow work runs on the caller's
  thread (never queued unboundedly, never deadlocks), and ``stop()`` is
  an idempotent bounded join wired into ``Node.stop()`` /
  ``ClusterNode.stop()``.

Accounting: ``search.batcher.{batched,bypass,window_waits,dispatches}``
metrics, a ``queue`` profiler phase on batched profiled members, and
per-member ``batched`` group size + ``queue_wait_ms`` on the insight
records (rolled up as ``batched_group_size`` per signature).
"""

from __future__ import annotations

import contextvars
import queue
import threading
import time
from typing import Optional

from opensearch_tpu.common.telemetry import metrics as _metrics

# Dynamic settings (search.batcher.*) land on module globals, the same
# idiom as executor.DEFAULT_ALLOW_PARTIAL_RESULTS: Node's
# _cluster/settings consumers write them, the engine reads them per
# request.  BATCHER_WINDOW_MS == 0 means "auto": use the measured
# insights coalesce window (AUTO_WINDOW_MS mirrors the dynamic
# search.insights.coalesce_window_ms setting).
BATCHER_ENABLED = True
BATCHER_WINDOW_MS = 0.0
BATCHER_MAX_BATCH = 64
AUTO_WINDOW_MS = 10.0

# request-body keys the continuous batcher understands; anything else
# (sort, aggs, collapse, rescore, highlight, ...) bypasses to the
# sequential path — strictly narrower than msearch's plan_batches so a
# coalesced response can never differ from the sequential one
_BATCHABLE_KEYS = frozenset({"query", "size", "from", "_source",
                             "profile", "track_total_hits"})


class SearchThreadpool:
    """Bounded, named-daemon-thread worker pool for the engine.

    Workers spawn lazily on first use and respawn after ``stop()`` (the
    pool is process-global; one node stopping must not strand another
    live node's searches).  ``run_all`` preserves submission order and
    runs overflow work inline on the caller's thread, so it can never
    deadlock on its own queue.  Submitted callables run under a copy of
    the caller's context (insight sinks, current task, trace spans all
    propagate).
    """

    def __init__(self, size: Optional[int] = None, queue_cap: int = 256):
        import os
        self.size = int(size or max(2, min(8, os.cpu_count() or 4)))
        self.queue_cap = int(queue_cap)
        self._q: "queue.Queue" = queue.Queue(self.queue_cap)
        self._threads: list[threading.Thread] = []
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._spawned = 0
        self.inline_runs = 0
        self.submitted = 0

    def _ensure_workers(self) -> bool:
        with self._lock:
            self._threads = [t for t in self._threads if t.is_alive()]
            while len(self._threads) < self.size:
                self._spawned += 1
                t = threading.Thread(
                    target=self._worker,
                    name=f"search-engine-{self._spawned}", daemon=True)
                t.start()
                self._threads.append(t)
            return bool(self._threads)

    def _worker(self):
        self._tls.in_worker = True
        while True:
            item = self._q.get()
            if item is None:           # stop sentinel
                return
            fn, ctx, slot = item
            try:
                slot["result"] = ctx.run(fn)
            except BaseException as e:  # noqa: BLE001 — re-raised by waiter
                slot["error"] = e
            finally:
                slot["event"].set()

    def run_all(self, fns: list) -> list:
        """Run callables concurrently; results in submission order.  The
        first raised exception (by submission order) re-raises on the
        caller's thread after every callable finished.

        Called FROM a pool worker, everything runs inline instead:
        nested fan-out (a pooled msearch-fallback search whose own host
        fast path fans out) must never park a worker waiting on
        subtasks only another worker can run — with all workers waiting,
        the queue would deadlock."""
        if getattr(self._tls, "in_worker", False):
            self.inline_runs += len(fns)
            return [fn() for fn in fns]
        slots = []
        for fn in fns:
            slot: dict = {"event": threading.Event()}
            ctx = contextvars.copy_context()
            submitted = False
            if self._ensure_workers():
                try:
                    self._q.put_nowait((fn, ctx, slot))
                    self.submitted += 1
                    submitted = True
                except queue.Full:
                    pass
            if not submitted:
                # caller-runs overflow policy: bounded queue + guaranteed
                # progress (and the only behavior once stop() drained us
                # mid-flight)
                self.inline_runs += 1
                try:
                    slot["result"] = ctx.run(fn)
                except BaseException as e:  # noqa: BLE001
                    slot["error"] = e
                slot["event"].set()
            slots.append(slot)
        for slot in slots:
            slot["event"].wait()
        for slot in slots:
            if "error" in slot:
                raise slot["error"]
        return [slot["result"] for slot in slots]

    def stop(self, timeout: float = 5.0):
        """Idempotent bounded join: sends one sentinel per live worker
        and joins each against a shared deadline.  Safe without any
        prior use; a later ``run_all`` simply respawns workers."""
        with self._lock:
            threads, self._threads = self._threads, []
        for _ in threads:
            self._q.put(None)
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))

    def stats(self) -> dict:
        with self._lock:
            alive = sum(1 for t in self._threads if t.is_alive())
        return {"threads": alive, "size": self.size,
                "submitted": self.submitted,
                "inline_runs": self.inline_runs}


class _Member:
    """One parked search inside an open batch group."""

    __slots__ = ("body", "bind", "event", "rows", "total", "max_score",
                 "error", "group_size", "wait_s", "stats", "path",
                 "gprof")

    def __init__(self, body: dict, bind: dict):
        self.body = body
        self.bind = bind
        self.event = threading.Event()
        self.rows = None
        self.total = 0
        self.max_score = None
        self.error: Optional[BaseException] = None
        self.group_size = 1
        self.wait_s = 0.0
        self.stats = {"pruned": 0, "scanned": 0}
        self.path = "host_batched"
        self.gprof = None


class _OpenGroup:
    __slots__ = ("key", "members", "sealed")

    def __init__(self, key):
        self.key = key
        self.members: list[_Member] = []
        self.sealed = False


class ContinuousBatcher:
    """Coalesce concurrent identical-shape searches into shared batch
    dispatches (module docstring).  Leader-driven: the first member of a
    group waits out the Δt window on its own request thread, then runs
    the whole group as one ``BatchGroup`` dispatch — no dedicated
    batcher thread exists, so there is nothing to leak or hang on
    shutdown.  Followers park on an event; every member renders its own
    response (and emits its own insight record) back on its own thread.
    """

    # backstop for follower waits: window + group execution; a leader
    # death (should be impossible — errors propagate to members) makes
    # the follower fall back to the sequential path instead of hanging
    FOLLOWER_TIMEOUT_S = 60.0

    def __init__(self):
        self._cond = threading.Condition()
        self._groups: dict[tuple, _OpenGroup] = {}
        self._active = 0           # in-flight batchable searches
        self._parked = 0
        self.max_parked = 256

    # -- sizing ------------------------------------------------------------

    @staticmethod
    def effective_window_s() -> float:
        w = BATCHER_WINDOW_MS if BATCHER_WINDOW_MS > 0 else AUTO_WINDOW_MS
        return max(0.0, float(w)) / 1000.0

    @staticmethod
    def simulate_occupancy(arrivals: list, window_s: float) -> float:
        """Deterministic replay of the grouping rule over ``(t,
        signature)`` arrival tuples: an arrival joins the open group of
        its signature when it lands within ``window_s`` of that group's
        LEADER, else it starts a new group.  Returns mean realized
        batch occupancy (arrivals per group) — the quantity the
        insights coalescability report predicts (its chain rule coalesces
        within-window successors, so it upper-bounds this)."""
        open_leader: dict = {}
        groups = 0
        for t, sig in sorted(arrivals):
            lead = open_leader.get(sig)
            if lead is not None and t - lead <= window_s:
                continue
            open_leader[sig] = t
            groups += 1
        return len(arrivals) / groups if groups else 0.0

    # -- admission ---------------------------------------------------------

    @staticmethod
    def _batchable(searcher, body: dict):
        """(plan, bind, k) when the body can take the batched kernel
        with response semantics identical to the sequential path, else
        None.  Narrower than msearch's plan_batches: only the keys the
        batch path fully reproduces are allowed (track_total_hits:false
        is excluded because sequential k-th pruning may legally return
        lower-bound totals there).

        The plan comes from a PEEK at the searcher's compiled-plan
        cache — never a compile: a first-seen shape runs the sequential
        path (which compiles it, with exact plan-cache miss
        attribution) and becomes batchable from its second arrival on.
        The zipf head the batcher amortizes is by definition the
        already-cached shapes."""
        import json as _json

        from opensearch_tpu.search import plan as P

        if set(body) - _BATCHABLE_KEYS:
            return None
        if int(body.get("from", 0) or 0) != 0:
            return None
        if body.get("track_total_hits") is False:
            return None
        k = int(body.get("size", 10) if body.get("size") is not None
                else 10)
        if k <= 0 or not searcher.segments:
            return None
        cache = getattr(searcher, "_plan_cache", None)
        if cache is None:
            return None
        try:
            ckey = (_json.dumps(body.get("query"), sort_keys=True,
                                separators=(",", ":")), True)
        except (TypeError, ValueError):
            return None
        out = cache.get(ckey)
        if out is None:
            return None
        plan, bind = out
        if not isinstance(plan, P.TermBagPlan) or not plan.scored:
            return None
        return plan, bind, k

    # -- execution ---------------------------------------------------------

    def execute(self, searcher, body: dict) -> Optional[dict]:
        """Serve one single-search body through the batcher, or return
        None to bypass (non-batchable).  A batchable body that finds no
        companions runs the plain sequential pipeline HERE, inside the
        in-flight count — that live count is the concurrency evidence a
        later arrival uses to decide the window wait is worth paying."""
        parsed = self._batchable(searcher, body)
        if parsed is None:
            _metrics().counter("search.batcher.bypass").inc()
            return None
        plan, bind, k = parsed
        t0 = time.monotonic()
        with self._cond:
            self._active += 1
        try:
            resp = self._coalesce(searcher, body, plan, bind, k, t0)
            if resp is not None:
                return resp
            # solo: no concurrent batchable traffic — zero added
            # latency, same sequential pipeline as ever
            return searcher.search(body)
        finally:
            with self._cond:
                self._active -= 1

    def _coalesce(self, searcher, body, plan, bind, k,
                  t0: float) -> Optional[dict]:
        key = (id(searcher), plan.field, k)
        member = _Member(body, bind)
        window = self.effective_window_s()
        with self._cond:
            g = self._groups.get(key)
            if g is not None and not g.sealed \
                    and len(g.members) < BATCHER_MAX_BATCH \
                    and self._parked < self.max_parked:
                g.members.append(member)
                self._parked += 1
                if len(g.members) >= BATCHER_MAX_BATCH:
                    g.sealed = True
                    self._groups.pop(key, None)
                    self._cond.notify_all()
                follower = True
            else:
                # no joinable group: this request leads.  It only parks
                # (and pays the window) when concurrent batchable
                # traffic exists RIGHT NOW — serial traffic sees
                # _active == 1 and proceeds with zero added latency.
                follower = False
                concurrent = (self._active > 1 or self._parked > 0)
                if not (concurrent and window > 0
                        and self._parked < self.max_parked):
                    return None            # solo: sequential path
                g = _OpenGroup(key)
                g.members.append(member)
                self._groups[key] = g
        if follower:
            if not member.event.wait(window + self.FOLLOWER_TIMEOUT_S):
                return None        # leader vanished: degrade, don't hang
            if member.error is not None:
                raise member.error
            member.wait_s = time.monotonic() - t0
            return self._render(searcher, member, t0)
        # leader: wait out the window (a max_batch seal wakes us early),
        # then run the whole group on this thread
        _metrics().counter("search.batcher.window_waits").inc()
        deadline = t0 + window
        with self._cond:
            while not g.sealed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            g.sealed = True
            self._groups.pop(key, None)
            members = list(g.members)
            self._parked -= max(0, len(members) - 1)
        member.wait_s = time.monotonic() - t0
        if len(members) == 1:
            # nobody arrived: don't pay the batch kernel's padding for a
            # group of one — the sequential path serves it
            return None
        try:
            self._run_group(searcher, plan.field, k, members)
        except BaseException as e:     # noqa: BLE001 — fan the error out
            for m in members:
                m.error = e
                m.event.set()
            raise
        for m in members:
            m.event.set()
        return self._render(searcher, member, t0)

    def _run_group(self, searcher, field: str, k: int,
                   members: list[_Member]):
        """ONE batched dispatch for the whole group (the leader's
        thread).  Reuses the msearch BatchGroup machinery — host or
        device backend chosen exactly like msearch, results
        byte-identical to the sequential path by the PR-5 invariant.
        Every member shares (field, k) by group-key construction."""
        from opensearch_tpu.ops import bm25 as bm25_ops
        from opensearch_tpu.search.batch import BatchGroup

        gprof = None
        if any((m.body or {}).get("profile") for m in members):
            from opensearch_tpu.search.profile import QueryProfiler
            gprof = QueryProfiler()
            gprof.set("plan_cache", "batched")
        group = BatchGroup(field, k)
        for i, m in enumerate(members):
            group.add(i, m.bind)
        if gprof is not None:
            gprof.set("batch", {"field": field, "k": k,
                                "queries": len(members),
                                "continuous": True})
        out = group.run(searcher, prof=gprof)
        path = ("host_batched" if bm25_ops.host_scoring_enabled()
                else "device_batched")
        _metrics().counter("search.batcher.dispatches").inc()
        _metrics().counter("search.batcher.batched").inc(len(members))
        for i, m in enumerate(members):
            rows, total, mx = out.get(i, ([], 0, None))
            m.rows, m.total, m.max_score = rows, total, mx
            m.group_size = len(members)
            m.stats = dict(group.last_stats)
            m.path = path
            m.gprof = gprof

    def _render(self, searcher, member: _Member, t0: float) -> dict:
        """Per-member response + insight record, on the member's OWN
        thread (so its contextvar insight sink and task attribution
        apply)."""
        from opensearch_tpu.search import insights
        from opensearch_tpu.search.executor import shards_section

        body = member.body or {}
        hits = searcher._hits_from_rows(member.rows or [],
                                        body.get("_source"))
        took_s = time.monotonic() - t0
        resp = {
            "took": int(took_s * 1000),
            "timed_out": False,
            "_shards": shards_section(1),
            "hits": {"total": {"value": int(member.total),
                               "relation": "eq"},
                     "max_score": member.max_score,
                     "hits": hits},
        }
        insights.emit(
            signature=insights.canonical_query(body.get("query")),
            scored=True,
            took_ms=took_s * 1000,
            execution_path=member.path,
            plan_cache="batched",
            pruned=member.stats.get("pruned", 0),
            scanned=member.stats.get("scanned", 0),
            batched=member.group_size,
            queue_wait_ms=member.wait_s * 1000)
        if member.gprof is not None and body.get("profile"):
            # members share the group profiler's phases (that sharing IS
            # the coalescing attribution) plus their OWN queue wait
            from opensearch_tpu.search.profile import QueryProfiler
            mprof = QueryProfiler()
            mprof.phases = dict(member.gprof.phases)
            mprof.counts = dict(member.gprof.counts)
            mprof.attrs = dict(member.gprof.attrs)
            mprof.segments = list(member.gprof.segments)
            mprof._xla0 = member.gprof._xla0
            mprof.add("queue", member.wait_s)
            resp["profile"] = {"shards": [mprof.shard_section(
                searcher.index_name, searcher.shard_id,
                plan_type="TermBagPlan",
                description=(f"continuous batch member of "
                             f"{member.group_size}"),
                total_segments=len(searcher.segments))]}
        return resp

    def stats(self) -> dict:
        m = _metrics()
        with self._cond:
            open_groups = len(self._groups)
            parked = self._parked
        return {
            "enabled": bool(BATCHER_ENABLED),
            "window_ms": (BATCHER_WINDOW_MS if BATCHER_WINDOW_MS > 0
                          else AUTO_WINDOW_MS),
            "max_batch": int(BATCHER_MAX_BATCH),
            "open_groups": open_groups,
            "parked": parked,
            "batched": m.counter("search.batcher.batched").value,
            "bypass": m.counter("search.batcher.bypass").value,
            "window_waits":
                m.counter("search.batcher.window_waits").value,
            "dispatches":
                m.counter("search.batcher.dispatches").value,
        }


class QueryEngine:
    """The unified entry.  Callers hand it a point-in-time
    ``ShardSearcher`` (and, at the REST edge, the owning
    ``IndexService``); backends — mesh collective, continuous batch,
    host fast path, device kernels — are decisions inside, never
    separately-wired code paths."""

    def __init__(self):
        self.pool = SearchThreadpool()
        self.batcher = ContinuousBatcher()

    def execute(self, searcher, body: Optional[dict] = None, *,
                agg_partials: bool = False, service=None) -> dict:
        """One search body -> one response.  ``service`` (an
        IndexService) enables the service-scoped backends: the mesh
        router and the continuous batcher (both need a stable searcher
        identity across requests, which only the service's cached
        searcher provides — the cluster data-node path builds a fresh
        per-payload searcher and therefore runs the plain pipeline)."""
        body = body or {}
        if service is not None and not agg_partials \
                and service._use_mesh(body):
            return service._mesh_search(body)
        if service is not None and not agg_partials and BATCHER_ENABLED:
            out = self.batcher.execute(searcher, body)
            if out is not None:
                return out
        return searcher.search(body, agg_partials=agg_partials)

    def msearch(self, searcher, bodies: list) -> list[dict]:
        """The multi-search entry: same-shape bodies coalesce into the
        batched kernel, the rest fan out over the engine threadpool
        (see ShardSearcher.msearch for the partitioning)."""
        return searcher.msearch(bodies)

    def count(self, searcher, query: Optional[dict] = None) -> int:
        return searcher.count(query)

    def shutdown(self):
        """Idempotent bounded-join shutdown (Node.stop /
        ClusterNode.stop).  The engine is process-global, so this only
        quiesces worker threads; another live node's next search
        respawns them."""
        self.pool.stop()

    def stats(self) -> dict:
        return {"threadpool": self.pool.stats(),
                "batcher": self.batcher.stats()}


_engine = QueryEngine()


def query_engine() -> QueryEngine:
    return _engine
