"""Task management: every request runs as a registered, cancellable task.

Analog of the reference's TaskManager/CancellableTask (ref
tasks/TaskManager.java:1, CancellableTask.java,
TaskCancellationService.java).  Long device work cooperates by calling
``Task.ensure_not_cancelled()`` between per-segment programs — the same
granularity as the reference's CancellableBulkScorer checking between
Lucene leaf scorers — so a runaway query stops at the next segment
boundary instead of holding the device until completion.

PR 4 adds the TaskResourceTrackingService half (ref
tasks/TaskResourceTrackingService.java): each task accumulates CPU time
(``time.thread_time`` deltas taken at the same cooperative checkpoints
that check cancellation), elapsed time, and a heap estimate charged
against the request circuit breaker — the numbers the search
backpressure service ranks runaway queries by — plus parent-task bans
(ref TaskManager.setBan) so a coordinator-side cancellation propagates
to the shard tasks it spawned on other nodes.
"""

from __future__ import annotations

import contextvars
import threading
import time
from typing import Callable, Optional

from opensearch_tpu.common.errors import OpenSearchTpuError

_current: "contextvars.ContextVar[Optional[Task]]" = \
    contextvars.ContextVar("opensearch_tpu_task", default=None)


def set_current(task: "Task"):
    task.start_thread_tracking()
    return _current.set(task)


def reset_current(token) -> None:
    t = _current.get()
    if t is not None:
        t.stop_thread_tracking()
    _current.reset(token)


def current() -> "Optional[Task]":
    return _current.get()


def check_current() -> None:
    """Cooperative cancellation point — cheap no-op without a task.
    Doubles as the resource-tracking checkpoint: the reference samples
    thread CPU at the same points it checks for cancellation."""
    t = _current.get()
    if t is not None:
        t.record_checkpoint()
        t.ensure_not_cancelled()


def charge_current(obj_or_bytes, label: str = "<task>") -> int:
    """Charge a heap estimate to the current task (no-op without one).
    Raises CircuitBreakingError when the request breaker would trip —
    the same degrade-per-shard path any breaker trip takes."""
    t = _current.get()
    if t is None:
        return 0
    return t.charge_heap(obj_or_bytes, label=label)


class TaskCancelledException(OpenSearchTpuError):
    status = 400


class Task:
    def __init__(self, task_id: int, action: str, description: str,
                 cancellable: bool = True,
                 headers: Optional[dict] = None,
                 parent_task_id: Optional[str] = None):
        self.id = task_id
        self.action = action
        self.description = description
        self.cancellable = cancellable
        # request-attribution headers (the reference threads X-Opaque-Id
        # from the REST request into every task it spawns — ref
        # tasks/Task.java HEADERS_TO_COPY)
        self.headers: dict = dict(headers or {})
        # "node_id:task_id" of the task that spawned this one on the
        # coordinator (ref Task.getParentTaskId) — the ban key
        self.parent_task_id = parent_task_id
        self.start_time_millis = int(time.time() * 1000)  # wall-clock: timestamp
        self._start = time.monotonic()
        self._cancelled = threading.Event()
        self.cancel_reason: Optional[str] = None
        self._listeners: list[Callable[[], None]] = []
        # -- resource tracking (TaskResourceTrackingService analog) ----
        self._res_lock = threading.Lock()
        self._cpu_nanos = 0
        self._cpu_base: dict[int, float] = {}   # thread id -> thread_time
        self._heap_bytes = 0
        self._heap_peak = 0
        self._checkpoints = 0

    # -- cancellation ------------------------------------------------------

    def cancel(self, reason: str = "by user request"):
        if not self.cancellable:
            raise OpenSearchTpuError(
                f"task [{self.id}] is not cancellable")
        self.cancel_reason = reason
        already = self._cancelled.is_set()
        self._cancelled.set()
        if not already:
            self._run_listeners()

    def add_cancellation_listener(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` once when this task is cancelled (immediately if it
        already was) — the reference's CancellableTask listener used to
        propagate bans and free held contexts."""
        run_now = False
        with self._res_lock:
            if self._cancelled.is_set():
                run_now = True
            else:
                self._listeners.append(fn)
        if run_now:
            try:
                fn()
            except Exception:  # noqa: BLE001 — listener isolation
                pass

    def _run_listeners(self) -> None:
        with self._res_lock:
            listeners, self._listeners = self._listeners, []
        for fn in listeners:
            try:
                fn()
            except Exception:  # noqa: BLE001 — listener isolation
                pass

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def ensure_not_cancelled(self):
        if self._cancelled.is_set():
            raise TaskCancelledException(
                f"task [{self.id}] was cancelled: {self.cancel_reason}")

    # -- resource tracking -------------------------------------------------

    def start_thread_tracking(self) -> None:
        """Baseline this thread's CPU clock; deltas accumulate at each
        cooperative checkpoint.  A task may execute on several threads
        over its life (REST thread, transport executor) — each tracks
        its own baseline."""
        with self._res_lock:
            self._cpu_base[threading.get_ident()] = time.thread_time()

    def stop_thread_tracking(self) -> None:
        tid = threading.get_ident()
        with self._res_lock:
            base = self._cpu_base.pop(tid, None)
            if base is not None:
                self._cpu_nanos += max(
                    0, int((time.thread_time() - base) * 1e9))

    def record_checkpoint(self) -> None:
        """Fold the calling thread's CPU delta into the task total (the
        reference refreshes ThreadMXBean CPU numbers at the same
        cancellation checkpoints)."""
        tid = threading.get_ident()
        now = time.thread_time()
        with self._res_lock:
            base = self._cpu_base.get(tid)
            if base is not None:
                self._cpu_nanos += max(0, int((now - base) * 1e9))
            self._cpu_base[tid] = now
            self._checkpoints += 1

    def add_cpu_nanos(self, nanos: int) -> None:
        """Explicit CPU attribution (device programs burn accelerator
        time the host thread clock never sees; tests charge synthetic
        usage deterministically)."""
        with self._res_lock:
            self._cpu_nanos += int(nanos)

    def charge_heap(self, obj_or_bytes, label: str = "<task>") -> int:
        """Reserve a heap estimate against the request breaker on behalf
        of this task; released in full when the task unregisters."""
        from opensearch_tpu.common.breakers import breaker_service
        from opensearch_tpu.common.cache import estimate_weight

        n = (int(obj_or_bytes) if isinstance(obj_or_bytes, (int, float))
             else estimate_weight(obj_or_bytes))
        if n <= 0:
            return 0
        breaker_service().request.add_estimate(
            n, label=f"task [{self.id}] {label}")
        with self._res_lock:
            self._heap_bytes += n
            self._heap_peak = max(self._heap_peak, self._heap_bytes)
        return n

    def release_resources(self) -> None:
        """Give back every breaker byte this task reserved (unregister
        path — mirrors TaskResourceTrackingService.stopTracking)."""
        from opensearch_tpu.common.breakers import breaker_service
        with self._res_lock:
            n, self._heap_bytes = self._heap_bytes, 0
            self._cpu_base.clear()
        if n:
            breaker_service().request.release(n)

    @property
    def cpu_time_nanos(self) -> int:
        with self._res_lock:
            return self._cpu_nanos

    @property
    def elapsed_nanos(self) -> int:
        return int((time.monotonic() - self._start) * 1e9)

    @property
    def heap_bytes(self) -> int:
        with self._res_lock:
            return self._heap_bytes

    def resource_stats(self) -> dict:
        with self._res_lock:
            return {"cpu_time_in_nanos": self._cpu_nanos,
                    "elapsed_time_in_nanos": self.elapsed_nanos,
                    "heap_size_in_bytes": self._heap_bytes,
                    "peak_heap_size_in_bytes": self._heap_peak,
                    "checkpoints": self._checkpoints}

    def info(self) -> dict:
        out = {"id": self.id, "action": self.action,
               "description": self.description,
               "cancellable": self.cancellable,
               "cancelled": self.cancelled,
               "start_time_in_millis": self.start_time_millis,
               "running_time_in_nanos": int(
                   (time.monotonic() - self._start) * 1e9),
               "resource_stats": self.resource_stats()}
        if self.parent_task_id:
            out["parent_task_id"] = self.parent_task_id
        if self.headers:
            out["headers"] = dict(self.headers)
        return out


class TaskManager:
    # bans are removed when the parent completes; the cap bounds damage
    # if an unban frame is lost (oldest bans fall off first)
    MAX_BANS = 1000

    def __init__(self, node_name: str = "node"):
        self.node_name = node_name
        self._lock = threading.Lock()
        self._tasks: dict[int, Task] = {}
        self._next = 0
        # parent_task_id -> ban reason (ref TaskManager.banedParents):
        # children registered AFTER the ban arrive pre-cancelled
        self._bans: dict[str, str] = {}

    def register(self, action: str, description: str = "",
                 cancellable: bool = True,
                 headers: Optional[dict] = None,
                 parent_task_id: Optional[str] = None) -> Task:
        with self._lock:
            self._next += 1
            t = Task(self._next, action, description, cancellable,
                     headers=headers, parent_task_id=parent_task_id)
            self._tasks[t.id] = t
            ban = (self._bans.get(parent_task_id)
                   if parent_task_id else None)
        if ban is not None and cancellable:
            # the race the reference closes with setBan: the ban beat
            # the child registration, so the child never starts work
            t.cancel(f"parent task was cancelled [{ban}]")
        return t

    def unregister(self, task: Task):
        task.release_resources()
        with self._lock:
            self._tasks.pop(task.id, None)

    def get(self, task_id: int) -> Optional[Task]:
        with self._lock:
            return self._tasks.get(task_id)

    def list(self, actions: Optional[str] = None) -> list[Task]:
        with self._lock:
            tasks = list(self._tasks.values())
        if actions:
            import fnmatch
            pats = [a.strip() for a in actions.split(",") if a.strip()]
            tasks = [t for t in tasks
                     if any(fnmatch.fnmatch(t.action, p) for p in pats)]
        return tasks

    def cancel(self, task_id: Optional[int] = None,
               actions: Optional[str] = None,
               reason: str = "by user request") -> list[Task]:
        """Cancel one task by id, or every (cancellable) task matching
        the actions pattern; returns the tasks flagged."""
        if task_id is not None:
            t = self.get(task_id)
            if t is None:
                return []
            t.cancel(reason)
            return [t]
        out = []
        for t in self.list(actions):
            if t.cancellable and not t.cancelled:
                t.cancel(reason)
                out.append(t)
        return out

    # -- parent bans (coordinator → data-node cancellation) ----------------

    def ban_parent(self, parent_task_id: str,
                   reason: str = "parent task was cancelled") -> list[Task]:
        """Cancel every registered child of ``parent_task_id`` and record
        the ban so late-arriving children are cancelled on registration
        (ref TaskCancellationService.setBanOnNodes)."""
        with self._lock:
            while len(self._bans) >= self.MAX_BANS:
                self._bans.pop(next(iter(self._bans)))
            self._bans[parent_task_id] = reason
            children = [t for t in self._tasks.values()
                        if t.parent_task_id == parent_task_id]
        out = []
        for t in children:
            if t.cancellable and not t.cancelled:
                t.cancel(reason)
                out.append(t)
        return out

    def unban_parent(self, parent_task_id: str) -> bool:
        with self._lock:
            return self._bans.pop(parent_task_id, None) is not None

    def banned_parents(self) -> dict:
        with self._lock:
            return dict(self._bans)
