"""Task management: every request runs as a registered, cancellable task.

Analog of the reference's TaskManager/CancellableTask (ref
tasks/TaskManager.java:1, CancellableTask.java,
TaskCancellationService.java).  Long device work cooperates by calling
``Task.ensure_not_cancelled()`` between per-segment programs — the same
granularity as the reference's CancellableBulkScorer checking between
Lucene leaf scorers — so a runaway query stops at the next segment
boundary instead of holding the device until completion.
"""

from __future__ import annotations

import contextvars
import threading
import time
from typing import Optional

from opensearch_tpu.common.errors import OpenSearchTpuError

_current: "contextvars.ContextVar[Optional[Task]]" = \
    contextvars.ContextVar("opensearch_tpu_task", default=None)


def set_current(task: "Task"):
    return _current.set(task)


def reset_current(token) -> None:
    _current.reset(token)


def current() -> "Optional[Task]":
    return _current.get()


def check_current() -> None:
    """Cooperative cancellation point — cheap no-op without a task."""
    t = _current.get()
    if t is not None:
        t.ensure_not_cancelled()


class TaskCancelledException(OpenSearchTpuError):
    status = 400


class Task:
    def __init__(self, task_id: int, action: str, description: str,
                 cancellable: bool = True,
                 headers: Optional[dict] = None):
        self.id = task_id
        self.action = action
        self.description = description
        self.cancellable = cancellable
        # request-attribution headers (the reference threads X-Opaque-Id
        # from the REST request into every task it spawns — ref
        # tasks/Task.java HEADERS_TO_COPY)
        self.headers: dict = dict(headers or {})
        self.start_time_millis = int(time.time() * 1000)  # wall-clock: timestamp
        self._start = time.monotonic()
        self._cancelled = threading.Event()
        self.cancel_reason: Optional[str] = None

    def cancel(self, reason: str = "by user request"):
        if not self.cancellable:
            raise OpenSearchTpuError(
                f"task [{self.id}] is not cancellable")
        self.cancel_reason = reason
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def ensure_not_cancelled(self):
        if self._cancelled.is_set():
            raise TaskCancelledException(
                f"task [{self.id}] was cancelled: {self.cancel_reason}")

    def info(self) -> dict:
        out = {"id": self.id, "action": self.action,
               "description": self.description,
               "cancellable": self.cancellable,
               "cancelled": self.cancelled,
               "start_time_in_millis": self.start_time_millis,
               "running_time_in_nanos": int(
                   (time.monotonic() - self._start) * 1e9)}
        if self.headers:
            out["headers"] = dict(self.headers)
        return out


class TaskManager:
    def __init__(self, node_name: str = "node"):
        self.node_name = node_name
        self._lock = threading.Lock()
        self._tasks: dict[int, Task] = {}
        self._next = 0

    def register(self, action: str, description: str = "",
                 cancellable: bool = True,
                 headers: Optional[dict] = None) -> Task:
        with self._lock:
            self._next += 1
            t = Task(self._next, action, description, cancellable,
                     headers=headers)
            self._tasks[t.id] = t
            return t

    def unregister(self, task: Task):
        with self._lock:
            self._tasks.pop(task.id, None)

    def get(self, task_id: int) -> Optional[Task]:
        with self._lock:
            return self._tasks.get(task_id)

    def list(self, actions: Optional[str] = None) -> list[Task]:
        with self._lock:
            tasks = list(self._tasks.values())
        if actions:
            import fnmatch
            pats = [a.strip() for a in actions.split(",") if a.strip()]
            tasks = [t for t in tasks
                     if any(fnmatch.fnmatch(t.action, p) for p in pats)]
        return tasks

    def cancel(self, task_id: Optional[int] = None,
               actions: Optional[str] = None,
               reason: str = "by user request") -> list[Task]:
        """Cancel one task by id, or every (cancellable) task matching
        the actions pattern; returns the tasks flagged."""
        if task_id is not None:
            t = self.get(task_id)
            if t is None:
                return []
            t.cancel(reason)
            return [t]
        out = []
        for t in self.list(actions):
            if t.cancellable and not t.cancelled:
                t.cancel(reason)
                out.append(t)
        return out
