"""Exception hierarchy, analog of OpenSearchException and friends
(reference: server/src/main/java/org/opensearch/OpenSearchException.java).

Every exception carries an HTTP status so the REST layer can serialize it the
way the reference's RestController does (rest/RestController.java:250) —
``{"error": {"type": ..., "reason": ...}, "status": N}``.
"""

from __future__ import annotations


class OpenSearchTpuError(Exception):
    status = 500

    def __init__(self, reason: str = "", **metadata):
        super().__init__(reason)
        self.reason = reason
        self.metadata = metadata

    #: explicit wire name when the reference's differs from the derived one
    wire_name: str | None = None

    @property
    def error_type(self) -> str:
        # CamelCase -> snake_case with the reference's `_exception` suffix
        # (OpenSearchException.getExceptionName) — clients and the YAML
        # conformance suites match on these exact strings.
        if self.wire_name is not None:
            return self.wire_name
        name = type(self).__name__
        out = []
        for i, ch in enumerate(name):
            if ch.isupper() and i > 0:
                out.append("_")
            out.append(ch.lower())
        s = "".join(out)
        if s.endswith("_error"):
            s = s[: -len("_error")] + "_exception"
        return s

    def to_xcontent(self) -> dict:
        return {
            "error": {
                "root_cause": [{"type": self.error_type,
                                "reason": self.reason}],
                "type": self.error_type,
                "reason": self.reason,
                **({"metadata": self.metadata} if self.metadata else {}),
            },
            "status": self.status,
        }


class ResourceNotFoundError(OpenSearchTpuError):
    status = 404


class IndexNotFoundError(ResourceNotFoundError):
    wire_name = "index_not_found_exception"

    def __init__(self, index: str):
        super().__init__(f"no such index [{index}]", index=index)


class DocumentMissingError(ResourceNotFoundError):
    def __init__(self, index: str, doc_id: str):
        super().__init__(f"[{doc_id}]: document missing", index=index)


class ResourceAlreadyExistsError(OpenSearchTpuError):
    status = 400


class IndexAlreadyExistsError(ResourceAlreadyExistsError):
    wire_name = "resource_already_exists_exception"

    def __init__(self, index: str):
        super().__init__(f"index [{index}] already exists", index=index)


class ValidationError(OpenSearchTpuError):
    """Bad request payloads (action/ValidateActions analog)."""

    wire_name = "action_request_validation_exception"
    status = 400


class ParsingError(ValidationError):
    """Malformed query DSL / mapping / settings JSON
    (core/common/ParsingException analog)."""

    wire_name = None                 # derived: parsing_exception


class MapperParsingError(ValidationError):
    """Document does not fit the mapping
    (index/mapper/MapperParsingException analog)."""

    wire_name = None                 # derived: mapper_parsing_exception


class StrictDynamicMappingError(MapperParsingError):
    """Unmapped field under ``dynamic: strict``
    (index/mapper/StrictDynamicMappingException analog)."""

    def __init__(self, path: str):
        super().__init__(
            f"mapping set to strict, dynamic introduction of [{path}] is not allowed"
        )


class IllegalArgumentError(ValidationError):
    wire_name = None                 # derived: illegal_argument_exception


class VersionConflictError(OpenSearchTpuError):
    """Optimistic concurrency failure (index/engine/VersionConflictEngineException)."""

    wire_name = "version_conflict_engine_exception"
    status = 409

    def __init__(self, doc_id: str, expected, actual):
        super().__init__(
            f"[{doc_id}]: version conflict, required [{expected}], current [{actual}]"
        )


class PrimaryFencedError(OpenSearchTpuError):
    """The node executing a write no longer holds the primary slot at the
    current primary term — a replica fenced its replication op, or the
    routing entry moved on before the ack (index/shard/ShardNotInPrimaryMode
    / the reference's isPrimaryMode fencing).

    503, not 409: the WRITE may well succeed against the new primary — the
    coordinator/client should re-route and retry, never treat the fence as
    a document-level conflict.  Critically this is raised INSTEAD of an
    ack: an op that was fenced is not durable and must not be reported as
    such."""

    status = 503


class CircuitBreakingError(OpenSearchTpuError):
    """Memory budget exceeded (common/breaker/CircuitBreakingException)."""

    status = 429

    def __init__(self, breaker: str, wanted: int, limit: int):
        super().__init__(
            f"[{breaker}] data for would be [{wanted}] bytes, larger than limit [{limit}]",
            breaker=breaker,
            bytes_wanted=wanted,
            limit=limit,
        )


class ClusterBlockException(OpenSearchTpuError):
    """Operation rejected by an index-level block, e.g. writes to a
    searchable-snapshot index (cluster/block/ClusterBlockException)."""

    status = 403


class TaskCancelledError(OpenSearchTpuError):
    status = 400


class EngineClosedError(OpenSearchTpuError):
    status = 500


class ShardNotFoundError(ResourceNotFoundError):
    pass


class NodeDisconnectedError(OpenSearchTpuError):
    """Transport-level peer failure (transport/NodeDisconnectedException).

    503, not 500: the condition is transient from the caller's side —
    retry against another copy / later — and the REST layer surfaces it
    as service-unavailable with the error type intact."""

    status = 503


class NoShardAvailableError(OpenSearchTpuError):
    """Every copy of a shard failed (NoShardAvailableActionException)."""

    wire_name = "no_shard_available_action_exception"
    status = 503


class NodeDuressError(OpenSearchTpuError):
    """Coordinator-side load shed: every in-sync copy of the shard
    reported duress, so the query phase fails fast into
    ``_shards.failures[]`` instead of queueing onto a collapsing node
    (429-class — the client should back off and retry)."""

    wire_name = "node_duress_exception"
    status = 429
    retry_after_seconds = 1


class SearchPhaseExecutionError(OpenSearchTpuError):
    """Shard failures the coordinator could not paper over — raised when
    partial results are disallowed (``allow_partial_search_results:
    false``) or no shard answered at all
    (action/search/SearchPhaseExecutionException)."""

    wire_name = "search_phase_execution_exception"
    status = 503

    def __init__(self, phase: str, reason: str,
                 shard_failures: "list[dict] | None" = None):
        super().__init__(reason)
        self.phase = phase
        self.shard_failures = shard_failures or []

    def to_xcontent(self) -> dict:
        out = super().to_xcontent()
        out["error"]["phase"] = self.phase
        out["error"]["failed_shards"] = self.shard_failures
        return out
