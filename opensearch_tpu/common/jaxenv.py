"""Central JAX configuration for the framework.

Import this module before any `jax` use inside opensearch_tpu.  It enables
x64 so int64 doc-value columns (date millis, longs — ref
server/src/main/java/org/opensearch/index/mapper/NumberFieldMapper.java,
DateFieldMapper) keep full precision on device.  XLA emulates s64 on TPU
with int32 pairs; the hot scoring kernels below explicitly use
int32/float32 so the MXU path is unaffected.
"""

import jax

jax.config.update("jax_enable_x64", True)
