"""Central JAX configuration for the framework.

Import this module before any `jax` use inside opensearch_tpu.  It enables
x64 so int64 doc-value columns (date millis, longs — ref
server/src/main/java/org/opensearch/index/mapper/NumberFieldMapper.java,
DateFieldMapper) keep full precision on device.  XLA emulates s64 on TPU
with int32 pairs; the hot scoring kernels below explicitly use
int32/float32 so the MXU path is unaffected.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: TPU compiles through the accelerator
# tunnel cost tens of seconds each, and the query engine compiles one
# program per (plan shape, size bucket) — caching them on disk makes
# every process after the first start warm (the same role Lucene's
# per-segment codec state plays for reopen cost).  Harmless on CPU
# (fast compiles, small files).
_cache_dir = os.environ.get(
    "OSTPU_XLA_CACHE", os.path.join(
        os.path.expanduser("~"), ".cache", "opensearch_tpu_xla",
        # scope per requested platform: TPU-host and forced-CPU compiles
        # record different machine-feature flags, and cross-loading them
        # warns about potential SIGILL
        (os.environ.get("JAX_PLATFORMS") or "default").replace(",", "_")))
try:
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:            # config name drift across jax versions
    pass
