"""Persistent tasks: background jobs that survive node restarts.

Analog of the reference's persistent-tasks framework (ref server/src/
main/java/org/opensearch/persistent/PersistentTasksService.java:47,
PersistentTasksCustomMetadata in cluster state): a task is submitted
with an action name + params, durably recorded BEFORE it starts, and —
unlike the plain TaskManager's in-flight tasks — re-executed from its
params after a crash/restart.  Single-node analog: the durable record
lives in ``persistent_tasks.json`` under the data path instead of
replicated cluster state; executors are registered per action name and
must be idempotent (the reference makes the same demand of its
PersistentTasksExecutor implementations).
"""

from __future__ import annotations

import json
import os
import threading
import uuid
from typing import Callable, Optional

from opensearch_tpu.common.errors import (IllegalArgumentError,
                                          ResourceNotFoundError)


class PersistentTasksService:
    def __init__(self, data_path: str):
        self.path = os.path.join(data_path, "persistent_tasks.json")
        self._lock = threading.RLock()
        self._executors: dict[str, Callable[[dict], dict]] = {}
        self._threads: dict[str, threading.Thread] = {}
        self._tasks: dict[str, dict] = {}
        if os.path.exists(self.path):
            with open(self.path) as f:
                self._tasks = json.load(f)

    MAX_TERMINAL = 100   # completed/failed records kept for polling

    def _persist(self):
        # terminal tasks are kept only for status polling; the reference
        # removes them from cluster state on completion — an unbounded
        # ledger would grow persist latency and boot time forever
        terminal = [tid for tid, t in self._tasks.items()
                    if t["state"] != "started"]
        for tid in terminal[:-self.MAX_TERMINAL or None]:
            del self._tasks[tid]
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._tasks, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def register_executor(self, action: str,
                          fn: Callable[[dict], dict]):
        """``fn(params) -> result`` runs in a background thread; it MUST
        be idempotent — a crash between start and completion re-runs it
        at the next boot."""
        self._executors[action] = fn

    def submit(self, action: str, params: dict) -> str:
        if action not in self._executors:
            raise IllegalArgumentError(
                f"unknown persistent task action [{action}]")
        task_id = uuid.uuid4().hex[:16]
        with self._lock:
            self._tasks[task_id] = {"action": action, "params": params,
                                    "state": "started"}
            self._persist()              # durable BEFORE execution
        self._spawn(task_id)
        return task_id

    def _spawn(self, task_id: str):
        def run():
            t = self._tasks[task_id]
            try:
                result = self._executors[t["action"]](t["params"])
                state, extra = "completed", {"result": result}
            except Exception as e:  # noqa: BLE001 — recorded, not raised
                state, extra = "failed", {"error": f"{type(e).__name__}: "
                                                   f"{e}"}
            with self._lock:
                self._tasks[task_id] = {**t, "state": state, **extra}
                self._persist()
                self._threads.pop(task_id, None)

        th = threading.Thread(target=run, daemon=True,
                              name=f"persistent-task-{task_id}")
        with self._lock:
            self._threads[task_id] = th
        th.start()

    def resume_incomplete(self):
        """Boot-time recovery: re-execute every task that was recorded
        but never reached a terminal state (the reference reassigns such
        tasks when their node leaves)."""
        with self._lock:
            pending = [tid for tid, t in self._tasks.items()
                       if t["state"] == "started"
                       and t["action"] in self._executors]
        for tid in pending:
            self._spawn(tid)
        return pending

    def get_or_none(self, task_id: str) -> Optional[dict]:
        with self._lock:
            t = self._tasks.get(task_id)
            return None if t is None else {"id": task_id, **t}

    def get(self, task_id: str) -> dict:
        t = self.get_or_none(task_id)
        if t is None:
            raise ResourceNotFoundError(
                f"persistent task [{task_id}] not found")
        return t

    def list(self) -> list[dict]:           # noqa: A003
        with self._lock:
            return [{"id": tid, **t}
                    for tid, t in sorted(self._tasks.items())]

    def wait(self, task_id: str, timeout: float = 30.0) -> dict:
        th = self._threads.get(task_id)
        if th is not None:
            th.join(timeout)
        return self.get(task_id)
