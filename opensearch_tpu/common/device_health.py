"""Accelerator fault tolerance: per-kernel-class circuit breakers.

The entire hot path of this engine runs through ONE accelerator —
staging (H2D), XLA dispatch, and the D2H result fetch — which makes the
device a single fault domain none of the cluster-level fault tolerance
(PRs 2-8) ever covered: a staging RESOURCE_EXHAUSTED, a compile
failure, a wedged dispatch, or a NaN-poisoned result used to surface as
a 500 or silent garbage.  This module is the degradation brain:

- ``DeviceHealthService`` keeps one circuit breaker per KERNEL CLASS
  (``staging`` = H2D transfers, ``dispatch`` = the per-segment query
  programs, ``batch`` = the msearch/continuous-batch kernel, ``mesh`` =
  the device-collective scatter-gather).  Each breaker walks the
  classic state machine: *closed* (healthy) -> *open* after
  ``failure_threshold`` consecutive device errors (counted in
  ``device.breaker.trips``) -> *half_open* once ``open_interval_s`` has
  elapsed (probe traffic allowed) -> *closed* again on a successful
  probe (``device.breaker.closes``) or back to *open* on a failed one.

- While a breaker is open, callers degrade instead of dispatching:
  scored term-bags score on the host impact tables BYTE-IDENTICALLY
  (the PR-5/PR-11 invariant — ``use_host`` in ``ShardSearcher._topk``),
  batch groups fall back to ``BatchGroup._run_host`` (same invariant),
  the mesh demotes to the counted ``_host_scatter_search`` fallback,
  and plans with no host fallback degrade into PR-2-style partial
  ``_shards.failures[]`` via ``DeviceDegradedError`` instead of 500s.

- ``is_device_error`` is the classifier: jax/jaxlib runtime errors
  (``XlaRuntimeError`` et al.), allocator ``MemoryError``, and the
  seeded faults ``testing/fault_injection.py::DeviceFaultInjector``
  injects (marked ``__device_fault__``).  Client errors (parsing,
  validation) and the request-breaker's ``CircuitBreakingError`` are
  NOT device errors — they must keep their own semantics.

- ``check_finite`` is the result-sanity guard used at the D2H sync
  regions: non-finite scores other than the ``-inf`` empty-slot
  sentinel mean the device returned poison; the caller discards them,
  recomputes on the host, counts ``device.poisoned_results`` and files
  a flight-recorder capture (``record_poison``).

The service is process-global like the residency ledger (in-process
multi-node tests share one device, so they honestly share one health
view); tests reset via ``device_health().reset()``.  Dynamic settings:
``device.health.{enabled,failure_threshold,open_interval_s}``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from opensearch_tpu.common.errors import OpenSearchTpuError
from opensearch_tpu.common.telemetry import metrics as _metrics

#: the kernel classes with their own breaker (callers may use others;
#: breakers are created on first record)
KERNEL_CLASSES = ("staging", "dispatch", "batch", "mesh")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class DeviceDegradedError(OpenSearchTpuError):
    """A device-side failure with no byte-identical host fallback: the
    search DEGRADES — partial ``_shards.failures[]`` at the coordinator
    (this error is in the PR-2 degradable class), never a 500."""

    status = 503


class DevicePoisonError(OpenSearchTpuError):
    """Non-finite scores read back from the device (the result-sanity
    guard's finding) — recorded as a dispatch failure so consecutive
    poison trips the breaker like any other device misbehavior."""

    status = 503
    __device_fault__ = True


def is_device_error(exc: BaseException) -> bool:
    """Device-fault classifier (module docstring).  Intentionally does
    NOT match the request/fielddata breaker's CircuitBreakingError (an
    admission decision, not a device fault) or client errors."""
    if getattr(exc, "__device_fault__", False):
        return True                # injected faults + DevicePoisonError
    if isinstance(exc, MemoryError):
        return True                # allocator exhaustion during staging
    for klass in type(exc).__mro__:
        mod = getattr(klass, "__module__", "") or ""
        if mod.startswith(("jaxlib", "jax.")) or mod == "jax":
            return True
        if klass.__name__ == "XlaRuntimeError":
            return True
    return False


def check_finite(vals) -> int:
    """Result-sanity guard for a device score array already synced to
    the host: returns the count of POISONED entries — NaN or +inf
    (``-inf`` is the legitimate empty-slot sentinel of every top-k
    kernel here).  0 means the result is sane."""
    import numpy as np

    a = np.asarray(vals)
    if a.dtype.kind not in "fc":
        return 0
    bad = ~np.isfinite(a) & ~np.isneginf(a)
    return int(bad.sum())


class _Breaker:
    """One kernel class's circuit-breaker state."""

    __slots__ = ("kind", "state", "streak", "trips", "closes",
                 "failures", "successes", "opened_at", "last_error")

    def __init__(self, kind: str):
        self.kind = kind
        self.state = CLOSED
        self.streak = 0            # consecutive failures while closed
        self.trips = 0
        self.closes = 0
        self.failures = 0
        self.successes = 0
        self.opened_at: Optional[float] = None
        self.last_error: Optional[str] = None

    def to_dict(self, now: float) -> dict:
        out = {"state": self.state, "consecutive_failures": self.streak,
               "trips": self.trips, "closes": self.closes,
               "failures": self.failures, "successes": self.successes}
        if self.opened_at is not None and self.state != CLOSED:
            out["open_for_ms"] = round((now - self.opened_at) * 1000.0, 3)
        if self.last_error:
            out["last_error"] = self.last_error
        return out


class DeviceHealthService:
    """Per-kernel-class circuit breakers over an injectable clock
    (module docstring).  ``allow`` / ``record_success`` /
    ``record_failure`` are the whole caller contract."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self.enabled = True
        self.failure_threshold = 3
        self.open_interval_s = 30.0
        self.poisoned_results = 0
        self._breakers: dict[str, _Breaker] = {
            k: _Breaker(k) for k in KERNEL_CLASSES}

    # -- settings consumers ------------------------------------------------

    def set_enabled(self, v: bool) -> None:
        self.enabled = bool(v)

    def set_failure_threshold(self, v: int) -> None:
        self.failure_threshold = max(1, int(v))

    def set_open_interval_s(self, v: float) -> None:
        self.open_interval_s = max(0.0, float(v))

    # -- the caller contract -----------------------------------------------

    def _breaker(self, kind: str) -> _Breaker:
        b = self._breakers.get(kind)
        if b is None:
            with self._lock:
                b = self._breakers.setdefault(kind, _Breaker(kind))
        return b

    def allow(self, kind: str) -> bool:
        """May this kernel class dispatch to the device right now?
        False only while the breaker is OPEN inside its cooldown; once
        ``open_interval_s`` elapses the breaker moves to half-open and
        the next requests run as probes (their outcome closes or
        re-opens it)."""
        if not self.enabled:
            return True
        b = self._breaker(kind)
        if b.state == CLOSED:
            return True
        with self._lock:
            if b.state == OPEN:
                if (b.opened_at is not None
                        and self._clock() - b.opened_at
                        >= self.open_interval_s):
                    b.state = HALF_OPEN
                else:
                    return False
            return b.state == HALF_OPEN

    def record_success(self, kind: str) -> None:
        """A device operation of this class completed sane: resets the
        failure streak; a half-open probe success re-closes the
        breaker."""
        b = self._breaker(kind)
        if b.state == CLOSED and b.streak == 0:
            b.successes += 1       # hot path: no lock, plain increment
            return
        with self._lock:
            b.successes += 1
            b.streak = 0
            if b.state != CLOSED:
                b.state = CLOSED
                b.opened_at = None
                b.closes += 1
        _metrics().counter("device.breaker.closes").inc()

    def record_failure(self, kind: str,
                       exc: Optional[BaseException] = None) -> None:
        """One device error of this class.  ``failure_threshold``
        consecutive errors trip the breaker open; a failure during
        half-open re-opens it immediately.  Marks ``exc`` so layered
        handlers (staging error re-caught at the dispatch site) don't
        double-count one fault."""
        if exc is not None:
            if getattr(exc, "_dh_recorded", False):
                return
            try:
                exc._dh_recorded = True
            except Exception:      # frozen/slotted exception: count anyway
                pass
        b = self._breaker(kind)
        now = self._clock()
        tripped = False
        with self._lock:
            b.failures += 1
            b.streak += 1
            if exc is not None:
                b.last_error = f"{type(exc).__name__}: {exc}"[:200]
            if self.enabled and b.state == HALF_OPEN:
                b.state = OPEN     # failed probe: back to cooldown
                b.opened_at = now
            elif self.enabled and b.state == CLOSED \
                    and b.streak >= self.failure_threshold:
                b.state = OPEN
                b.opened_at = now
                b.trips += 1
                tripped = True
        _metrics().counter("device.errors").inc()
        if tripped:
            _metrics().counter("device.breaker.trips").inc()
            from opensearch_tpu.common.telemetry import flight_recorder
            flight_recorder().record(
                "device_breaker_trip",
                f"device [{kind}] circuit breaker tripped after "
                f"{self.failure_threshold} consecutive errors",
                detail={"kernel_class": kind,
                        "failure_threshold": self.failure_threshold,
                        "last_error": b.last_error})

    def record_poison(self, *, kernel: str, segment: str = "-",
                      index: str = "-", shard=0, bad: int = 0) -> None:
        """The result-sanity guard found non-finite device scores: the
        caller has discarded them and is recomputing on the host; this
        files the evidence (counter + flight capture) and feeds the
        dispatch breaker so sustained poison trips it."""
        with self._lock:
            self.poisoned_results += 1
        _metrics().counter("device.poisoned_results").inc()
        from opensearch_tpu.common.telemetry import flight_recorder
        flight_recorder().record(
            "device_poisoned_result",
            f"non-finite scores from device kernel [{kernel}] on "
            f"[{index}][{shard}] segment [{segment}]: discarded and "
            "recomputed on host",
            detail={"kernel": kernel, "segment": segment, "index": index,
                    "shard": shard, "non_finite_values": int(bad)})
        self.record_failure(
            "batch" if kernel.startswith("batch") else "dispatch",
            DevicePoisonError(
                f"[{kernel}] returned {bad} non-finite scores"))

    # -- readout -----------------------------------------------------------

    def stats(self) -> dict:
        """The ``_nodes/stats`` ``device.health`` block."""
        now = self._clock()
        with self._lock:
            breakers = {k: b.to_dict(now)
                        for k, b in sorted(self._breakers.items())}
            poisoned = self.poisoned_results
        return {
            "enabled": self.enabled,
            "failure_threshold": self.failure_threshold,
            "open_interval_s": self.open_interval_s,
            "poisoned_results": poisoned,
            "breakers": breakers,
        }

    def breaker_states(self) -> dict:
        """{kind: state} snapshot (soak SLO assertions)."""
        with self._lock:
            return {k: b.state for k, b in self._breakers.items()}

    def tripped_kinds(self) -> list:
        """Kernel classes whose breaker tripped at least once."""
        with self._lock:
            return sorted(k for k, b in self._breakers.items()
                          if b.trips > 0)

    def prometheus_text(self) -> str:
        """Breaker-state gauges for the ``/_metrics`` scrape (trip and
        close counters already flow through the MetricsRegistry)."""
        s = self.stats()
        lines = [
            "# HELP opensearch_tpu_device_breaker_open Device kernel-"
            "class circuit breaker state (0 closed, 1 open, "
            "0.5 half-open)",
            "# TYPE opensearch_tpu_device_breaker_open gauge",
        ]
        val = {CLOSED: "0", HALF_OPEN: "0.5", OPEN: "1"}
        for kind, b in s["breakers"].items():
            kv = (str(kind).replace("\\", "\\\\").replace('"', '\\"'))
            lines.append(
                f'opensearch_tpu_device_breaker_open{{kernel="{kv}"}} '  # label-ok: bounded kernel classes
                f'{val.get(b["state"], "1")}')
        lines.append(
            "# HELP opensearch_tpu_device_poisoned_results_gauge "
            "Non-finite device results discarded by the sanity guard")
        lines.append(
            "# TYPE opensearch_tpu_device_poisoned_results_gauge gauge")
        lines.append(
            f"opensearch_tpu_device_poisoned_results_gauge "
            f"{s['poisoned_results']}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Test hook: all breakers back to closed, counters zeroed,
        thresholds back to defaults."""
        with self._lock:
            self._breakers = {k: _Breaker(k) for k in KERNEL_CLASSES}
            self.poisoned_results = 0
            self.enabled = True
            self.failure_threshold = 3
            self.open_interval_s = 30.0


_health = DeviceHealthService()


def device_health() -> DeviceHealthService:
    return _health
