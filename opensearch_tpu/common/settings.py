"""Typed, validated, dynamically-updatable settings registry.

Analog of the reference's Setting/ClusterSettings/IndexScopedSettings system
(common/settings/Setting.java:107, ClusterSettings.java,
IndexScopedSettings.java): every knob is a typed ``Setting`` with a scope, a
default (possibly computed from other settings), an optional validator, and a
``dynamic`` flag; registries reject unknown keys and notify update consumers
on live changes.
"""

from __future__ import annotations

import enum
import threading
from typing import Any, Callable, Generic, Iterable, Optional, TypeVar

from opensearch_tpu.common.errors import IllegalArgumentError

T = TypeVar("T")


class Scope(enum.Enum):
    NODE = "node"
    CLUSTER = "cluster"
    INDEX = "index"


_TIME_UNITS = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
_BYTE_UNITS = {
    "b": 1,
    "kb": 1024,
    "mb": 1024**2,
    "gb": 1024**3,
    "tb": 1024**4,
}


def parse_time(value) -> float:
    """'30s' / '500ms' / '1m' -> seconds (common/unit/TimeValue analog)."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip().lower()
    if s == "-1":
        return -1.0
    for suffix in sorted(_TIME_UNITS, key=len, reverse=True):
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * _TIME_UNITS[suffix]
    return float(s)


def parse_bytes(value) -> int:
    """'512mb' -> bytes (core/common/unit/ByteSizeValue analog)."""
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value).strip().lower()
    if s == "-1":
        return -1
    for suffix in sorted(_BYTE_UNITS, key=len, reverse=True):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * _BYTE_UNITS[suffix])
    return int(s)


def _parse_bool(value) -> bool:
    if isinstance(value, bool):
        return value
    s = str(value).strip().lower()
    if s in ("true", "1", "yes", "on"):
        return True
    if s in ("false", "0", "no", "off"):
        return False
    raise IllegalArgumentError(f"cannot parse boolean [{value}]")


class Setting(Generic[T]):
    def __init__(
        self,
        key: str,
        default: Any,
        parser: Callable[[Any], T] = lambda x: x,
        *,
        scope: Scope = Scope.NODE,
        dynamic: bool = False,
        validator: Optional[Callable[[T], None]] = None,
    ):
        self.key = key
        self._default = default
        self.parser = parser
        self.scope = scope
        self.dynamic = dynamic
        self.validator = validator

    def default(self, settings: "Settings") -> T:
        raw = self._default(settings) if callable(self._default) else self._default
        return self.parse(raw)

    def parse(self, raw: Any) -> T:
        try:
            value = self.parser(raw)
        except (TypeError, ValueError) as e:
            raise IllegalArgumentError(
                f"failed to parse value [{raw}] for setting [{self.key}]"
            ) from e
        if self.validator is not None:
            self.validator(value)
        return value

    def get(self, settings: "Settings") -> T:
        if settings.has(self.key):
            return self.parse(settings.get_raw(self.key))
        return self.default(settings)

    # -- constructors mirroring Setting.intSetting / boolSetting / ... -----

    @staticmethod
    def int_setting(key, default, *, min_value=None, max_value=None, **kw) -> "Setting[int]":
        def validate(v: int):
            if min_value is not None and v < min_value:
                raise IllegalArgumentError(f"[{key}] must be >= {min_value}, got {v}")
            if max_value is not None and v > max_value:
                raise IllegalArgumentError(f"[{key}] must be <= {max_value}, got {v}")

        return Setting(key, default, int, validator=validate, **kw)

    @staticmethod
    def float_setting(key, default, *, min_value=None, **kw) -> "Setting[float]":
        def validate(v: float):
            if min_value is not None and v < min_value:
                raise IllegalArgumentError(f"[{key}] must be >= {min_value}, got {v}")

        return Setting(key, default, float, validator=validate, **kw)

    @staticmethod
    def bool_setting(key, default, **kw) -> "Setting[bool]":
        return Setting(key, default, _parse_bool, **kw)

    @staticmethod
    def str_setting(key, default, *, choices: Optional[Iterable[str]] = None, **kw):
        def validate(v: str):
            if choices is not None and v not in set(choices):
                raise IllegalArgumentError(f"[{key}] must be one of {sorted(set(choices))}, got [{v}]")

        return Setting(key, default, str, validator=validate, **kw)

    @staticmethod
    def time_setting(key, default, **kw) -> "Setting[float]":
        return Setting(key, default, parse_time, **kw)

    @staticmethod
    def byte_size_setting(key, default, **kw) -> "Setting[int]":
        return Setting(key, default, parse_bytes, **kw)


class Settings:
    """Immutable flat key->raw-value map (common/settings/Settings.java).

    Nested dicts are flattened to dotted keys on construction, matching the
    reference's behavior of accepting both in yml/JSON bodies.
    """

    EMPTY: "Settings"

    def __init__(self, values: Optional[dict] = None):
        self._values: dict[str, Any] = {}
        if values:
            self._flatten("", values)

    def _flatten(self, prefix: str, obj: dict):
        for k, v in obj.items():
            key = f"{prefix}{k}"
            if isinstance(v, dict):
                self._flatten(key + ".", v)
            else:
                self._values[key] = v

    def has(self, key: str) -> bool:
        return key in self._values

    def get_raw(self, key: str, default=None):
        return self._values.get(key, default)

    def keys(self):
        return self._values.keys()

    def as_dict(self) -> dict:
        return dict(self._values)

    def as_nested_dict(self) -> dict:
        root: dict = {}
        for key, v in sorted(self._values.items()):
            parts = key.split(".")
            node = root
            ok = True
            for p in parts[:-1]:
                nxt = node.setdefault(p, {})
                if not isinstance(nxt, dict):
                    ok = False
                    break
                node = nxt
            if ok:
                node[parts[-1]] = v
            else:
                root[key] = v
        return root

    def merged_with(self, other: "Settings | dict") -> "Settings":
        if isinstance(other, dict):
            other = Settings(other)
        s = Settings()
        s._values = {**self._values, **other._values}
        return s

    def filtered(self, predicate) -> "Settings":
        s = Settings()
        s._values = {k: v for k, v in self._values.items() if predicate(k)}
        return s

    def __eq__(self, other):
        return isinstance(other, Settings) and self._values == other._values

    def __repr__(self):
        return f"Settings({self._values!r})"


Settings.EMPTY = Settings()


class SettingsRegistry:
    """Registry + live-update dispatch (ClusterSettings / IndexScopedSettings).

    ``apply_update`` validates that every key is registered and dynamic, then
    calls the consumers registered via ``add_settings_update_consumer``
    (the reference wires these at e.g. search/SearchService.java:360).
    """

    def __init__(self, settings: Settings, registered: Iterable[Setting]):
        self._lock = threading.RLock()
        self._registered: dict[str, Setting] = {}
        for s in registered:
            self.register(s)
        self._settings = settings
        self._consumers: list[tuple[Setting, Callable[[Any], None]]] = []
        self._prefixes: list[str] = []

    def register(self, setting: Setting):
        with self._lock:
            if setting.key in self._registered:
                raise IllegalArgumentError(f"setting [{setting.key}] already registered")
            self._registered[setting.key] = setting

    def register_prefix(self, prefix: str):
        """Allow ANY dynamic key under ``prefix.`` (the reference's affix
        settings, e.g. cluster.remote.<alias>.seeds)."""
        with self._lock:
            self._prefixes.append(prefix.rstrip(".") + ".")

    @property
    def settings(self) -> Settings:
        return self._settings

    def get(self, setting: Setting[T]) -> T:
        return setting.get(self._settings)

    def get_by_key(self, key: str):
        setting = self._registered.get(key)
        if setting is None:
            raise IllegalArgumentError(f"unknown setting [{key}]")
        return setting.get(self._settings)

    def add_settings_update_consumer(self, setting: Setting[T], consumer: Callable[[T], None]):
        with self._lock:
            if setting.key not in self._registered:
                raise IllegalArgumentError(f"setting [{setting.key}] not registered")
            self._consumers.append((setting, consumer))

    def validate(self, updates: dict, *, allow_static: bool = False):
        for key, raw in updates.items():
            setting = self._registered.get(key)
            if setting is None:
                if any(key.startswith(p) for p in self._prefixes):
                    continue       # affix keys accept any value
                raise IllegalArgumentError(
                    f"unknown setting [{key}], please check that any required plugins"
                    " are installed, or check the breaking changes documentation"
                )
            if not setting.dynamic and not allow_static:
                raise IllegalArgumentError(f"final or non-dynamic setting [{key}], not updateable")
            if raw is not None:
                setting.parse(raw)

    def apply_update(self, updates: dict):
        """Apply dynamic updates; ``None`` values reset the key to default."""
        with self._lock:
            self.validate(updates)
            new = dict(self._settings.as_dict())
            for key, raw in updates.items():
                if raw is None:
                    new.pop(key, None)
                else:
                    new[key] = raw
            old = self._settings
            self._settings = Settings(new)
            for setting, consumer in self._consumers:
                if setting.key in updates:
                    consumer(setting.get(self._settings))
            return old
