"""Named, bounded thread pools with stats.

Analog of the reference's ThreadPool (ref threadpool/ThreadPool.java:83;
pool names at :99-111): work is segregated by concern so a flood of one
kind (bulk writes) can't starve another (searches), and every pool
reports active/queue/completed counts through ``_nodes/stats``.  Sizes
derive from the host core count like the reference's defaults.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from opensearch_tpu.common.errors import OpenSearchTpuError


class RejectedExecutionError(OpenSearchTpuError):
    status = 429
    # the REST layer maps this to 429 + Retry-After (overload is
    # transient by definition; tell clients when to come back)
    retry_after_seconds = 1


class _Pool:
    def __init__(self, name: str, size: int, queue_cap: int):
        self.name = name
        self.size = size
        self.queue_cap = queue_cap
        self._executor = ThreadPoolExecutor(
            max_workers=size, thread_name_prefix=f"opensearch[{name}]")
        self._lock = threading.Lock()
        self.active = 0
        self.queued = 0
        self.completed = 0
        self.rejected = 0

    def submit(self, fn, *args, **kwargs):
        with self._lock:
            if self.queued >= self.queue_cap:
                self.rejected += 1
                raise RejectedExecutionError(
                    f"rejected execution on [{self.name}]: queue "
                    f"capacity [{self.queue_cap}] reached")
            self.queued += 1

        def run():
            with self._lock:
                self.queued -= 1
                self.active += 1
            try:
                return fn(*args, **kwargs)
            finally:
                with self._lock:
                    self.active -= 1
                    self.completed += 1
        return self._executor.submit(run)

    def stats(self) -> dict:
        with self._lock:
            return {"threads": self.size, "queue": self.queued,
                    "active": self.active, "completed": self.completed,
                    "rejected": self.rejected}

    def shutdown(self):
        self._executor.shutdown(wait=False, cancel_futures=True)


class ThreadPool:
    """The node's pool registry (names mirror ThreadPool.Names)."""

    def __init__(self, cores: Optional[int] = None):
        n = cores or os.cpu_count() or 4
        self.pools: dict[str, _Pool] = {
            "search": _Pool("search", max(2, (3 * n) // 2), 1000),
            "write": _Pool("write", n, 10_000),
            "get": _Pool("get", n, 1000),
            "generic": _Pool("generic", max(4, n), 1000),
            "snapshot": _Pool("snapshot", max(1, n // 2), 200),
            "management": _Pool("management", max(1, n // 4), 100),
        }

    def executor(self, name: str) -> _Pool:
        pool = self.pools.get(name)
        if pool is None:
            raise OpenSearchTpuError(f"no thread pool named [{name}]")
        return pool

    def stats(self) -> dict:
        return {name: p.stats() for name, p in self.pools.items()}

    def shutdown(self):
        for p in self.pools.values():
            p.shutdown()
