"""RetryableAction: budget-capped exponential backoff with deterministic
seeded jitter.

Analog of ``action/support/RetryableAction.java`` (tryAction/
onFailure/retry scheduling) and ``action/bulk/BackoffPolicy.java``
(exponentialBackoff): a transient transport failure — dropped frame,
broken pipe, timed-out peer — is retried with growing delays until
either the attempt count or the wall budget is exhausted, then the last
error surfaces.  Everything is measured on the monotonic clock and the
jitter is drawn from a *seeded* RNG so fault-injection tests replay the
exact same schedule every run.

Counters land in the PR-1 MetricsRegistry (``retry.<name>.attempts`` /
``retry.<name>.retries`` / ``retry.<name>.exhausted``) and every attempt
runs under a ``retry:<name>`` span carrying the attempt number.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

from opensearch_tpu.common.errors import NodeDisconnectedError


def _transport_retryables() -> tuple:
    # late import: transport/service.py imports common.errors, and
    # ReceiveTimeoutError lives next to the transports
    import concurrent.futures
    from opensearch_tpu.transport.service import ReceiveTimeoutError
    # concurrent.futures.TimeoutError is NOT the builtin before 3.11
    return (NodeDisconnectedError, ReceiveTimeoutError, TimeoutError,
            concurrent.futures.TimeoutError)


class BackoffPolicy:
    """Delay schedule for retries: ``base * multiplier**n`` capped at
    ``max_delay``, with full-range deterministic jitter (the seeded-RNG
    variant of the reference's equal-jitter backoff).

    ``budget_s`` caps the TOTAL time an action may spend across attempts
    (sleeps included) so a retry loop can never outlive its caller's own
    timeout — the retryable-replication analog of
    ``indices.replication.retry_timeout``.
    """

    def __init__(self, name: str = "action", base_delay: float = 0.05,
                 multiplier: float = 2.0, max_delay: float = 2.0,
                 max_attempts: int = 4, budget_s: Optional[float] = None,
                 jitter: float = 0.2, seed: int = 0):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.name = name
        self.base_delay = float(base_delay)
        self.multiplier = float(multiplier)
        self.max_delay = float(max_delay)
        self.max_attempts = int(max_attempts)
        self.budget_s = budget_s
        self.jitter = float(jitter)
        self.seed = int(seed)

    def delays(self):
        """Deterministic delay sequence for attempts 2..max_attempts.
        A fresh seeded RNG per call: two actions sharing one policy see
        the identical schedule (reproducibility over spread)."""
        rng = random.Random(self.seed)
        d = self.base_delay
        for _ in range(self.max_attempts - 1):
            base = min(d, self.max_delay)
            # jitter shrinks the delay only (never beyond max_delay) and
            # is drawn deterministically from the seeded stream
            yield base * (1.0 - self.jitter * rng.random())
            d *= self.multiplier


class RetryExhaustedError(NodeDisconnectedError):
    """All attempts failed; carries the last underlying error."""

    def __init__(self, name: str, attempts: int, last: BaseException):
        super().__init__(
            f"[{name}] failed after {attempts} attempt(s): {last}")
        self.last = last


class RetryableAction:
    """Run ``fn`` with retries per ``policy``.

    ``retry_on`` defaults to the transport-transient trio
    (NodeDisconnectedError / ReceiveTimeoutError / TimeoutError); any
    other exception propagates immediately — a version conflict or a
    validation error must never be hammered.
    """

    def __init__(self, name: str, fn: Callable, policy: BackoffPolicy,
                 retry_on: Optional[tuple] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.fn = fn
        self.policy = policy
        self.retry_on = retry_on or _transport_retryables()
        self._sleep = sleep
        self._clock = clock

    def run(self):
        from opensearch_tpu.common.telemetry import metrics, tracer

        t0 = self._clock()
        budget = self.policy.budget_s
        attempts = 0
        last: Optional[BaseException] = None
        schedule = self.policy.delays()
        while True:
            attempts += 1
            metrics().counter(f"retry.{self.name}.attempts").inc()  # metric-name-ok: action names are code-level identifiers
            try:
                with tracer().start_span(f"retry:{self.name}",
                                         {"attempt": attempts}):
                    return self.fn()
            except self.retry_on as e:   # noqa: PERF203 — retry boundary
                last = e
            delay = next(schedule, None)
            out_of_budget = (budget is not None
                             and self._clock() - t0
                             + (delay or 0.0) > budget)
            if delay is None or out_of_budget:
                metrics().counter(f"retry.{self.name}.exhausted").inc()  # metric-name-ok: bounded set of action names
                raise RetryExhaustedError(self.name, attempts, last) \
                    from last
            metrics().counter(f"retry.{self.name}.retries").inc()  # metric-name-ok: bounded set of action names
            self._sleep(delay)   # backoff: schedule from BackoffPolicy


def retry_call(name: str, fn: Callable,
               policy: Optional[BackoffPolicy] = None,
               retry_on: Optional[tuple] = None, **policy_kw):
    """One-line form: ``retry_call("replicate", fn, max_attempts=3)``."""
    if policy is None:
        policy = BackoffPolicy(name=name, **policy_kw)
    return RetryableAction(name, fn, policy, retry_on=retry_on).run()


class Deadline:
    """Monotonic-clock deadline for bounded wait loops: carry one of
    these (or a BackoffPolicy) instead of sleeping bare in a loop — the
    ``tools/check_sleep_loops.py`` lint enforces the annotation."""

    __slots__ = ("_until",)

    def __init__(self, seconds: float):
        self._until = time.monotonic() + seconds

    def expired(self) -> bool:
        return time.monotonic() >= self._until

    def remaining(self) -> float:
        return max(0.0, self._until - time.monotonic())

    def wait_until(self, pred: Callable[[], bool],
                   poll: float = 0.02) -> bool:
        """Poll ``pred`` until true or the deadline expires."""
        ev = threading.Event()
        while not self.expired():
            if pred():
                return True
            ev.wait(min(poll, self.remaining()))   # deadline-bounded
        return pred()
