"""Indexing-pressure accounting: per-node and PER-SHARD in-flight
indexing bytes with 429 rejection past the limit.

Analog of ``index/ShardIndexingPressure.java`` +
``IndexingPressureService``: every write op charges its source size for
the duration of the operation; the node limit guards total memory, the
per-shard soft limit keeps one hot shard from starving the rest (the
reference's shard-level min/max granting).  Stats surface in
``_nodes/stats`` like the reference's ``indexing_pressure`` section.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from opensearch_tpu.common.errors import OpenSearchTpuError


class IndexingPressureRejection(OpenSearchTpuError):
    status = 429


class IndexingPressure:
    def __init__(self, limit_bytes: int = 64 << 20,
                 shard_fraction: float = 0.25):
        self.limit_bytes = int(limit_bytes)
        # one shard may hold at most this fraction of the node budget
        # while OTHER shards are also writing (soloists get the node
        # limit — ShardIndexingPressure's dynamic granting, simplified)
        self.shard_fraction = float(shard_fraction)
        self._lock = threading.Lock()
        self._current = 0
        self._per_shard: dict = {}
        self._total = 0                   # lifetime bytes
        self._rejections = 0
        self._shard_rejections: dict = {}

    @contextmanager
    def coordinating(self, shard_key, n_bytes: int):
        n_bytes = int(n_bytes)
        with self._lock:
            new_total = self._current + n_bytes
            if new_total > self.limit_bytes:
                self._rejections += 1
                self._shard_rejections[shard_key] = \
                    self._shard_rejections.get(shard_key, 0) + 1
                raise IndexingPressureRejection(
                    f"rejecting coordinating operation of [{n_bytes}] "
                    f"bytes: current [{self._current}] + operation would "
                    f"exceed [indexing_pressure.memory.limit] of "
                    f"[{self.limit_bytes}]")
            shard_now = self._per_shard.get(shard_key, 0) + n_bytes
            others_active = any(k != shard_key for k in self._per_shard)
            if others_active \
                    and shard_now > self.limit_bytes * self.shard_fraction:
                self._rejections += 1
                self._shard_rejections[shard_key] = \
                    self._shard_rejections.get(shard_key, 0) + 1
                raise IndexingPressureRejection(
                    f"rejecting coordinating operation of [{n_bytes}] "
                    f"bytes for shard [{shard_key}]: shard in-flight "
                    f"[{shard_now}] would exceed its share of "
                    f"[indexing_pressure.memory.limit]")
            self._current = new_total
            self._per_shard[shard_key] = shard_now
            self._total += n_bytes
        try:
            yield
        finally:
            with self._lock:
                self._current -= n_bytes
                left = self._per_shard.get(shard_key, 0) - n_bytes
                if left <= 0:
                    self._per_shard.pop(shard_key, None)
                else:
                    self._per_shard[shard_key] = left

    def stats(self) -> dict:
        with self._lock:
            return {
                "memory": {
                    "current": {"coordinating_in_bytes": self._current,
                                "per_shard": {
                                    f"[{k[0]}][{k[1]}]": v
                                    for k, v in self._per_shard.items()}},
                    "total": {"coordinating_in_bytes": self._total,
                              "coordinating_rejections": self._rejections},
                    "limit_in_bytes": self.limit_bytes,
                },
                "shard_rejections": {
                    f"[{k[0]}][{k[1]}]": v
                    for k, v in self._shard_rejections.items()},
            }
