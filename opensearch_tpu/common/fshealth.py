"""Filesystem health probe: write-and-fsync check marking the node
unhealthy when the data path can't take writes.

Analog of ``monitor/fs/FsHealthService.java:74,209`` — the reference
periodically writes a temp file and fsyncs it; repeated failures mark
the node unhealthy, which removes it from election eligibility and
surfaces in stats.  Here the probe is callable on demand (tests drive
it deterministically) and scheduled by the node's check loop.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional


class FsHealthService:
    PROBE_FILE = ".es_temp_file"          # the reference's probe name

    def __init__(self, data_path: str):
        self.data_path = data_path
        self._lock = threading.Lock()
        self._healthy = True
        self._last_error: Optional[str] = None
        self._last_check_ms: Optional[int] = None

    def check(self) -> bool:
        """One write+fsync probe; updates and returns health."""
        probe = os.path.join(self.data_path, self.PROBE_FILE)
        try:
            with open(probe, "wb") as f:
                f.write(b"probe")
                f.flush()
                os.fsync(f.fileno())
            os.remove(probe)
            ok, err = True, None
        except OSError as e:
            ok, err = False, f"{type(e).__name__}: {e}"
        with self._lock:
            self._healthy = ok
            self._last_error = err
            self._last_check_ms = int(time.time() * 1000)
        return ok

    @property
    def healthy(self) -> bool:
        with self._lock:
            return self._healthy

    def stats(self) -> dict:
        with self._lock:
            out = {"status": "healthy" if self._healthy else "unhealthy"}
            if self._last_error:
                out["reason"] = self._last_error
            if self._last_check_ms is not None:
                out["last_check_in_millis"] = self._last_check_ms
            return out
