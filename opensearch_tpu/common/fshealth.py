"""Filesystem health probe: write-and-fsync check marking the node
unhealthy when the data path can't take writes.

Analog of ``monitor/fs/FsHealthService.java:74,209`` — the reference
periodically writes a temp file and fsyncs it; repeated failures mark
the node unhealthy, which removes it from election eligibility and
surfaces in stats.  Here the probe is callable on demand (tests drive
it deterministically) and scheduled by the node's check loop.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional


class FsHealthService:
    PROBE_FILE = ".es_temp_file"          # the reference's probe name

    def __init__(self, data_path: str,
                 slow_path_logging_threshold_ms: Optional[float] = 5000.0):
        self.data_path = data_path
        # a probe slower than this marks the node unhealthy too — the
        # reference's fs_health.slow_path_logging_threshold: a disk that
        # takes seconds per fsync is as gone as one returning EIO
        self.slow_path_logging_threshold_ms = slow_path_logging_threshold_ms
        self._lock = threading.Lock()
        self._healthy = True
        self._last_error: Optional[str] = None
        self._last_check_ms: Optional[int] = None
        self._last_probe_elapsed_ms: Optional[int] = None
        self._probe_stop: Optional[threading.Event] = None
        self._probe_thread: Optional[threading.Thread] = None

    def check(self) -> bool:
        """One write+fsync probe; updates and returns health.  The probe
        is timed with a monotonic clock (the reference flags SLOW fsyncs
        too, FsHealthService.monitorFSHealth) — wall clock only stamps
        WHEN the check ran."""
        probe = os.path.join(self.data_path, self.PROBE_FILE)
        t0 = time.monotonic()
        try:
            with open(probe, "wb") as f:
                f.write(b"probe")
                f.flush()
                os.fsync(f.fileno())
            os.remove(probe)
            ok, err = True, None
        except OSError as e:
            ok, err = False, f"{type(e).__name__}: {e}"
        elapsed_ms = int((time.monotonic() - t0) * 1000)
        if (ok and self.slow_path_logging_threshold_ms is not None
                and elapsed_ms > self.slow_path_logging_threshold_ms):
            ok = False
            err = (f"fsync probe took {elapsed_ms}ms, above the "
                   f"{self.slow_path_logging_threshold_ms}ms slow-path "
                   "threshold")
        with self._lock:
            self._healthy = ok
            self._last_error = err
            self._last_check_ms = int(time.time() * 1000)  # wall-clock: timestamp
            self._last_probe_elapsed_ms = elapsed_ms
        return ok

    # -- periodic probe (the reference's scheduled monitorFSHealth) --------

    def start_probe(self, interval_s: float = 5.0, name: str = "fshealth"):
        """Run ``check()`` on a cadence in a daemon thread — disk death
        must be noticed BETWEEN stats reads, not just when somebody asks
        (the gap the module docstring promised and nothing implemented)."""
        with self._lock:
            if self._probe_thread is not None:
                return
            stop = self._probe_stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                try:
                    self.check()
                except Exception:  # noqa: BLE001 — probe must never die
                    pass
        t = threading.Thread(target=loop, name=f"{name}-probe", daemon=True)
        with self._lock:
            self._probe_thread = t
        t.start()

    def stop_probe(self, timeout: float = 2.0):
        with self._lock:
            stop, t = self._probe_stop, self._probe_thread
            self._probe_stop = self._probe_thread = None
        if stop is not None:
            stop.set()
        if t is not None:
            t.join(timeout=timeout)

    @property
    def healthy(self) -> bool:
        with self._lock:
            return self._healthy

    def stats(self) -> dict:
        with self._lock:
            out = {"status": "healthy" if self._healthy else "unhealthy"}
            if self._last_error:
                out["reason"] = self._last_error
            if self._last_check_ms is not None:
                out["last_check_in_millis"] = self._last_check_ms
            if self._last_probe_elapsed_ms is not None:
                out["probe_elapsed_in_millis"] = \
                    self._last_probe_elapsed_ms
            return out
