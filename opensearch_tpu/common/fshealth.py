"""Filesystem health probe: write-and-fsync check marking the node
unhealthy when the data path can't take writes.

Analog of ``monitor/fs/FsHealthService.java:74,209`` — the reference
periodically writes a temp file and fsyncs it; repeated failures mark
the node unhealthy, which removes it from election eligibility and
surfaces in stats.  Here the probe is callable on demand (tests drive
it deterministically) and scheduled by the node's check loop.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional


class FsHealthService:
    PROBE_FILE = ".es_temp_file"          # the reference's probe name

    def __init__(self, data_path: str):
        self.data_path = data_path
        self._lock = threading.Lock()
        self._healthy = True
        self._last_error: Optional[str] = None
        self._last_check_ms: Optional[int] = None
        self._last_probe_elapsed_ms: Optional[int] = None

    def check(self) -> bool:
        """One write+fsync probe; updates and returns health.  The probe
        is timed with a monotonic clock (the reference flags SLOW fsyncs
        too, FsHealthService.monitorFSHealth) — wall clock only stamps
        WHEN the check ran."""
        probe = os.path.join(self.data_path, self.PROBE_FILE)
        t0 = time.monotonic()
        try:
            with open(probe, "wb") as f:
                f.write(b"probe")
                f.flush()
                os.fsync(f.fileno())
            os.remove(probe)
            ok, err = True, None
        except OSError as e:
            ok, err = False, f"{type(e).__name__}: {e}"
        elapsed_ms = int((time.monotonic() - t0) * 1000)
        with self._lock:
            self._healthy = ok
            self._last_error = err
            self._last_check_ms = int(time.time() * 1000)  # wall-clock: timestamp
            self._last_probe_elapsed_ms = elapsed_ms
        return ok

    @property
    def healthy(self) -> bool:
        with self._lock:
            return self._healthy

    def stats(self) -> dict:
        with self._lock:
            out = {"status": "healthy" if self._healthy else "unhealthy"}
            if self._last_error:
                out["reason"] = self._last_error
            if self._last_check_ms is not None:
                out["last_check_in_millis"] = self._last_check_ms
            if self._last_probe_elapsed_ms is not None:
                out["probe_elapsed_in_millis"] = \
                    self._last_probe_elapsed_ms
            return out
