"""Blob-store SPI: the storage abstraction snapshots/remote-store hang on.

Mirrors the reference's ``common/blobstore`` package (BlobStore /
BlobContainer; ref repositories/blobstore/BlobStoreRepository.java:1 is
the main consumer): a *store* hands out *containers* (nested paths), and
containers read/write/list immutable blobs.  Writes are atomic —
readers never observe partial blobs (tmp + fsync + rename on the fs
impl; object stores give this for free).

The ``fs`` implementation is built in (the reference's repository-fs);
cloud backends (the reference's repository-s3/azure/gcs plugins) plug in
by registering a factory in ``BLOBSTORE_TYPES``.
"""

from __future__ import annotations

import os
import shutil
from typing import Callable, Iterator

from opensearch_tpu.common.errors import OpenSearchTpuError


class BlobStoreError(OpenSearchTpuError):
    status = 500


class NoSuchBlobError(BlobStoreError):
    status = 404


class BlobContainer:
    """One directory-like namespace of immutable blobs."""

    def read_blob(self, name: str) -> bytes:
        raise NotImplementedError

    def write_blob(self, name: str, data: bytes,
                   fail_if_exists: bool = False):
        raise NotImplementedError

    def blob_exists(self, name: str) -> bool:
        raise NotImplementedError

    def list_blobs(self) -> Iterator[str]:
        raise NotImplementedError

    def delete_blob(self, name: str):
        raise NotImplementedError

    def child(self, path: str) -> "BlobContainer":
        raise NotImplementedError


class BlobStore:
    def container(self, path: str = "") -> BlobContainer:
        raise NotImplementedError

    def delete(self):
        """Remove the whole store (repository cleanup)."""
        raise NotImplementedError


# ---------------------------------------------------------------------------
# fs implementation
# ---------------------------------------------------------------------------


class FsBlobContainer(BlobContainer):
    def __init__(self, root: str):
        self.root = root

    def _path(self, name: str) -> str:
        if "/" in name or name.startswith("."):
            raise BlobStoreError(f"invalid blob name [{name}]")
        return os.path.join(self.root, name)

    def read_blob(self, name: str) -> bytes:
        p = self._path(name)
        if not os.path.exists(p):
            raise NoSuchBlobError(f"blob [{name}] not found")
        with open(p, "rb") as f:
            return f.read()

    def write_blob(self, name: str, data: bytes,
                   fail_if_exists: bool = False):
        os.makedirs(self.root, exist_ok=True)
        p = self._path(name)
        if fail_if_exists and os.path.exists(p):
            raise BlobStoreError(f"blob [{name}] already exists")
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)

    def blob_exists(self, name: str) -> bool:
        return os.path.exists(self._path(name))

    def list_blobs(self) -> Iterator[str]:
        if not os.path.isdir(self.root):
            return iter(())
        return iter(sorted(n for n in os.listdir(self.root)
                           if not n.endswith(".tmp")))

    def delete_blob(self, name: str):
        p = self._path(name)
        if os.path.exists(p):
            os.remove(p)

    def child(self, path: str) -> "FsBlobContainer":
        safe = [s for s in path.split("/") if s and s not in (".", "..")]
        return FsBlobContainer(os.path.join(self.root, *safe))

    def list_children(self) -> list[str]:
        """Names of child containers (subdirectories)."""
        if not os.path.isdir(self.root):
            return []
        return sorted(n for n in os.listdir(self.root)
                      if os.path.isdir(os.path.join(self.root, n)))

    def delete_tree(self):
        """Remove this container and everything under it."""
        shutil.rmtree(self.root, ignore_errors=True)


class FsBlobStore(BlobStore):
    def __init__(self, settings: dict):
        location = settings.get("location")
        if not location:
            raise BlobStoreError(
                "[fs] repository requires a [location] setting")
        self.location = str(location)

    def container(self, path: str = "") -> FsBlobContainer:
        return FsBlobContainer(self.location).child(path) if path else \
            FsBlobContainer(self.location)

    def delete(self):
        shutil.rmtree(self.location, ignore_errors=True)


BLOBSTORE_TYPES: dict[str, Callable[[dict], BlobStore]] = {
    "fs": FsBlobStore,
}
