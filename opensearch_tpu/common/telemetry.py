"""Telemetry SPI: distributed tracing + metrics registry.

Analog of the reference's ``libs/telemetry`` (tracing/Tracer.java,
metrics/MetricsRegistry.java) with the OTel plugin's behavior folded in
at the fidelity this engine needs:

- ``Tracer``: contextvar-scoped spans carrying W3C trace-context ids
  (``traceparent`` header compatible, TracingContextPropagator analog).
  Finished spans land in a bounded in-memory exporter the
  ``GET /_nodes/trace`` debug endpoint reads — the InMemorySpanExporter
  technique from the reference's telemetry tests.
- ``MetricsRegistry``: named counters and fixed-bucket latency
  histograms with percentile readout, surfaced by ``_nodes/stats``
  under a ``telemetry`` section.

Timing uses ``time.monotonic`` (durations must never jump with wall
clock); span start/end wall timestamps are kept separately for display.
Everything is cheap enough to stay always-on: a span is one small object
and two dict writes, matching the reference's default no-sampling OTel
configuration in tests.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from bisect import bisect_left
from collections import deque
from typing import Optional

_current_span: "contextvars.ContextVar[Optional[Span]]" = \
    contextvars.ContextVar("opensearch_tpu_span", default=None)

TRACEPARENT = "traceparent"


class SpanContext:
    """The propagatable identity of a span (trace_id + span_id) — what
    crosses process/transport boundaries via ``traceparent``."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def to_traceparent(self) -> str:
        # W3C trace-context: version-traceid-spanid-flags (sampled)
        return f"00-{self.trace_id}-{self.span_id}-01"

    @staticmethod
    def from_traceparent(value) -> "Optional[SpanContext]":
        if not value or not isinstance(value, str):
            return None
        parts = value.strip().split("-")
        if len(parts) < 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
            return None
        try:
            int(parts[1], 16)
            int(parts[2], 16)
        except ValueError:
            return None
        return SpanContext(parts[1], parts[2])


class Span:
    """One timed operation.  ``end()`` freezes the duration and ships the
    span to the tracer's in-memory exporter."""

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_span_id: Optional[str],
                 attributes: Optional[dict] = None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_span_id = parent_span_id
        self.attributes: dict = dict(attributes or {})
        self.start_time_millis = int(time.time() * 1000)  # wall-clock: display timestamp
        self._start = time.monotonic()
        self.duration_nanos: Optional[int] = None
        self.error: Optional[str] = None

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attribute(self, key: str, value) -> "Span":
        self.attributes[key] = value
        return self

    def record_error(self, err) -> None:
        self.error = f"{type(err).__name__}: {err}"

    def end(self) -> None:
        if self.duration_nanos is not None:
            return                       # idempotent
        self.duration_nanos = int((time.monotonic() - self._start) * 1e9)
        self.tracer._export(self)

    def to_dict(self) -> dict:
        out = {"name": self.name, "trace_id": self.trace_id,
               "span_id": self.span_id,
               "parent_span_id": self.parent_span_id,
               "start_time_in_millis": self.start_time_millis,
               "duration_in_nanos": self.duration_nanos,
               "attributes": dict(self.attributes)}
        if self.error is not None:
            out["error"] = self.error
        return out


class Tracer:
    """Contextvar-scoped span stack + bounded finished-span buffer.

    ``start_span`` is a context manager: the new span becomes current for
    the ``with`` body, so nested instrumentation parents automatically;
    an explicit ``parent`` (a SpanContext extracted from transport
    headers) overrides the ambient current span — that is how remote
    shard executions join the coordinator's trace.
    """

    def __init__(self, max_spans: int = 2048):
        self._finished: "deque[dict]" = deque(maxlen=max_spans)
        self._lock = threading.Lock()

    # -- span lifecycle ---------------------------------------------------

    def begin_span(self, name: str, attributes: Optional[dict] = None,
                   parent: "SpanContext | Span | None" = None) -> Span:
        """Non-context-manager start (callers that end() across scopes)."""
        if parent is None:
            parent = _current_span.get()
        if parent is None:
            trace_id, parent_id = uuid.uuid4().hex, None
        else:
            trace_id = parent.trace_id
            parent_id = (parent.span_id if isinstance(parent, SpanContext)
                         else parent.span_id)
        return Span(self, name, trace_id, parent_id, attributes)

    @contextlib.contextmanager
    def start_span(self, name: str, attributes: Optional[dict] = None,
                   parent: "SpanContext | Span | None" = None):
        span = self.begin_span(name, attributes, parent)
        token = _current_span.set(span)
        try:
            yield span
        except BaseException as e:
            span.record_error(e)
            raise
        finally:
            _current_span.reset(token)
            span.end()

    @staticmethod
    def current() -> Optional[Span]:
        return _current_span.get()

    def _export(self, span: Span) -> None:
        with self._lock:
            self._finished.append(span.to_dict())

    # -- context propagation (TracingContextPropagator analog) ------------

    @staticmethod
    def inject(headers: dict) -> dict:
        """Write the current span's ``traceparent`` into ``headers`` (a
        no-op outside any span)."""
        span = _current_span.get()
        if span is not None:
            headers[TRACEPARENT] = span.context().to_traceparent()
        return headers

    @staticmethod
    def extract(headers: Optional[dict]) -> Optional[SpanContext]:
        if not headers:
            return None
        value = headers.get(TRACEPARENT)
        if value is None:            # HTTP headers arrive case-insensitive
            for k, v in headers.items():
                if str(k).lower() == TRACEPARENT:
                    value = v
                    break
        return SpanContext.from_traceparent(value)

    # -- readout ----------------------------------------------------------

    def recent(self, limit: int = 100,
               trace_id: Optional[str] = None) -> list[dict]:
        """Most-recent finished spans, newest first."""
        with self._lock:
            spans = list(self._finished)
        spans.reverse()
        if trace_id:
            spans = [s for s in spans if s["trace_id"] == trace_id]
        return spans[: max(0, int(limit))]

    def reset(self) -> None:
        with self._lock:
            self._finished.clear()


# default latency buckets in milliseconds (upper bounds; +inf implied) —
# the OTel explicit-bucket histogram shape the reference's metrics SPI
# defaults to, shifted down for sub-ms device dispatches
DEFAULT_BUCKETS_MS = (0.5, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000,
                      2500, 5000, 10000, 30000)


class Counter:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Histogram:
    """Fixed-bucket latency histogram with percentile readout.

    Percentiles interpolate within the winning bucket (the Prometheus
    ``histogram_quantile`` estimation), so p50/p99 stay meaningful
    without storing raw samples.
    """

    def __init__(self, name: str, buckets=DEFAULT_BUCKETS_MS):
        self.name = name
        self.buckets = tuple(float(b) for b in buckets)
        self._counts = [0] * (len(self.buckets) + 1)   # last = +inf
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    def observe(self, value_ms: float) -> None:
        value_ms = float(value_ms)
        idx = bisect_left(self.buckets, value_ms)
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += value_ms
            if value_ms > self._max:
                self._max = value_ms

    @property
    def count(self) -> int:
        return self._count

    def percentile(self, q: float) -> float:
        """q in [0, 100]; linear interpolation inside the target bucket."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            hi = self._max
        if total == 0:
            return 0.0
        rank = (q / 100.0) * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                up = self.buckets[i] if i < len(self.buckets) else hi
                # no estimate may exceed the observed maximum (the raw
                # bucket bound can overshoot badly for sparse data)
                up = max(lo, min(up, hi))
                frac = (rank - cum) / c
                return lo + (up - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return hi

    def bucket_counts(self) -> tuple:
        """Consistent snapshot of the raw histogram state:
        ``(bucket_upper_bounds, per_bucket_counts, count, sum_ms)`` —
        the last count is the +inf overflow bucket.  Both the JSON
        ``stats()`` readout and the Prometheus exposition render from
        THIS, so the two surfaces always report the same data."""
        with self._lock:
            return (self.buckets, list(self._counts), self._count,
                    self._sum)

    def stats(self) -> dict:
        buckets, counts, count, total = self.bucket_counts()
        with self._lock:
            mx = self._max
        out = {"count": count,
               "sum_in_millis": round(total, 3),
               "max_in_millis": round(mx, 3)}
        if count:
            out["avg_in_millis"] = round(total / count, 3)
            out["percentiles"] = {
                "50.0": round(self.percentile(50), 3),
                "90.0": round(self.percentile(90), 3),
                "99.0": round(self.percentile(99), 3)}
            # cumulative buckets (Prometheus ``le`` semantics): the raw
            # data behind the percentile estimates, so dashboards can
            # aggregate histograms across nodes correctly
            cum = 0
            rendered = []
            for le, c in zip(buckets, counts):
                cum += c
                rendered.append({"le": le, "count": cum})
            rendered.append({"le": "+Inf", "count": count})
            out["buckets"] = rendered
        return out


class MetricsRegistry:
    """Named counters + histograms (libs/telemetry MetricsRegistry
    analog).  Instruments are created on first use and live forever —
    matching the reference's register-once semantics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def histogram(self, name: str,
                  buckets=DEFAULT_BUCKETS_MS) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name,
                                                Histogram(name, buckets))
        return h

    @contextlib.contextmanager
    def time_ms(self, name: str):
        """Time a block into histogram ``name`` (milliseconds)."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            # pass-through: the metric-name lint enforces the CALLER's
            # literal, not this helper  # metric-name-ok
            self.histogram(name).observe((time.monotonic() - t0) * 1000)

    def stats(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            histograms = dict(self._histograms)
        return {"counters": {n: c.value
                             for n, c in sorted(counters.items())},
                "histograms": {n: h.stats()
                               for n, h in sorted(histograms.items())}}

    def prometheus_text(self) -> str:
        """Render the full registry in the Prometheus text exposition
        format (version 0.0.4): counters as ``<name>_total``, histograms
        as cumulative ``_bucket{le=...}`` series + ``_sum``/``_count``.
        Dotted metric names map to underscore-separated Prometheus
        names; histogram values are milliseconds (suffix ``_ms``).
        Served by ``GET /_metrics`` — the scrape surface for the same
        data ``_nodes/stats`` ``telemetry`` reports as JSON."""
        import re as _re

        def pn(name: str) -> str:
            return _re.sub(r"[^a-zA-Z0-9_:]", "_", name)

        def num(v: float) -> str:
            return f"{v:.10g}"

        with self._lock:
            counters = sorted(self._counters.items())
            histograms = sorted(self._histograms.items())
        lines = []
        for name, c in counters:
            p = pn(name) + "_total"
            lines.append(f"# HELP {p} Counter [{name}]")
            lines.append(f"# TYPE {p} counter")
            lines.append(f"{p} {c.value}")
        for name, h in histograms:
            p = pn(name)
            if not p.endswith("_ms"):    # unit suffix, never doubled
                p += "_ms"
            buckets, per_bucket, count, total = h.bucket_counts()
            lines.append(f"# HELP {p} Latency histogram [{name}] "
                         "(milliseconds)")
            lines.append(f"# TYPE {p} histogram")
            cum = 0
            for le, n in zip(buckets, per_bucket):
                cum += n
                lines.append(f'{p}_bucket{{le="{num(le)}"}} {cum}')  # label-ok: le values are the fixed code-level bucket bounds
            lines.append(f'{p}_bucket{{le="+Inf"}} {count}')  # label-ok: constant +Inf bound
            lines.append(f"{p}_sum {num(total)}")
            lines.append(f"{p}_count {count}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._histograms.clear()


class FlightRecorder:
    """Bounded ring of diagnostic captures taken the moment something
    already went wrong — a search slow-log threshold tripped, or a soak
    SLO breached (testing/workload.py attaches the capture to the
    breach verdict).  Each capture snapshots the recent finished spans
    and the counter registry, plus the trigger's own detail (slow query
    source, the slow query's profile when it ran with ``profile:true``,
    the breached SLO's limit/observed pair) — so a breach verdict ships
    with diagnosable evidence instead of a bare boolean.

    Always-on and cheap at steady state: recording only happens on
    trigger, the ring is bounded, and reads (``GET
    /_nodes/flight_recorder``) copy snapshots, never live state.
    """

    def __init__(self, max_captures: int = 32, span_limit: int = 64):
        self._ring: "deque[dict]" = deque(maxlen=max_captures)
        self._lock = threading.Lock()
        self.span_limit = int(span_limit)

    def record(self, trigger: str, reason: str,
               detail: Optional[dict] = None) -> dict:
        capture = {
            "trigger": trigger,
            "reason": reason,
            "timestamp_in_millis": int(time.time() * 1000),  # wall-clock
            "spans": tracer().recent(self.span_limit),
            "counters": dict(metrics().stats()["counters"]),
        }
        if detail:
            capture["detail"] = detail
        with self._lock:
            self._ring.append(capture)
        metrics().counter("flight_recorder.captures").inc()
        return capture

    def captures(self, limit: int = 32) -> list[dict]:
        """Most recent captures, newest first."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out[: max(0, int(limit))]

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()


# -- process-wide defaults (the breaker_service() singleton pattern) -----
#
# Multi-node-in-one-process tests share these; spans carry a ``node``
# attribute where the owning node matters.

_tracer = Tracer()
_metrics = MetricsRegistry()
_flight_recorder = FlightRecorder()


def tracer() -> Tracer:
    return _tracer


def metrics() -> MetricsRegistry:
    return _metrics


def flight_recorder() -> FlightRecorder:
    return _flight_recorder
