"""Generic thread-safe weighted-LRU cache building block.

Analog of the reference's ``common/cache/Cache.java`` (the CacheBuilder
family every higher-level cache — IndicesRequestCache, fielddata,
script — is built on): per-entry weigher, max-weight LRU eviction,
optional TTL, a removal listener carrying the removal reason, and a
stats readout (hits/misses/evictions/memory bytes).

Two integrations make this the ONLY sanctioned cache idiom in this
engine (``tools/check_ad_hoc_caches.py`` rejects raw dict-on-object
caches):

- **breakers** — an optional circuit breaker (an object from
  ``common/breakers.py`` or a child name resolved lazily against the
  installed service) is charged for every resident byte; when a put
  would trip it the cache first evicts its own LRU tail to make room
  and, failing that, skips caching instead of dying — memory pressure
  degrades hit rate, never correctness.
- **telemetry** — hit/miss/eviction counters stream into the metrics
  registry as ``cache.<name>.{hits,misses,evictions}`` so
  ``_nodes/stats`` exposes every cache without bespoke plumbing.

The lock is a plain RLock around an OrderedDict: removal listeners run
under it and must not re-enter the cache.  ``clock`` is injectable so
TTL tests never sleep.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict
from typing import Callable, Optional

from opensearch_tpu.common.breakers import CircuitBreakingError
from opensearch_tpu.common.telemetry import metrics as _metrics

# removal reasons (RemovalNotification.RemovalReason analog)
EXPLICIT = "explicit"        # invalidate()/invalidate_all()/invalidate_if()
REPLACED = "replaced"        # put() over an existing key
EVICTED = "evicted"          # weight pressure pushed it out
EXPIRED = "expired"          # TTL ran out


def estimate_weight(obj) -> int:
    """Cheap recursive byte estimate for cache weighers: exact for
    bytes/str/ndarray-likes, structural for containers, 8 for scalars.
    Deliberately NOT sys.getsizeof — device arrays report their buffer
    via ``nbytes``, which is the number that matters for budgets."""
    if obj is None:
        return 8
    nbytes = getattr(obj, "nbytes", None)
    if nbytes is not None:               # numpy / jax arrays
        return int(nbytes)
    if isinstance(obj, (bytes, bytearray)):
        return len(obj)
    if isinstance(obj, str):
        return 2 * len(obj) + 40
    if isinstance(obj, (int, float, bool)):
        return 8
    if isinstance(obj, dict):
        return 64 + sum(estimate_weight(k) + estimate_weight(v)
                        for k, v in obj.items())
    if isinstance(obj, (list, tuple, set, frozenset)):
        return 56 + sum(estimate_weight(v) for v in obj)
    import sys
    try:
        return sys.getsizeof(obj)
    except TypeError:
        return 64


def _default_weigher(key, value) -> int:
    return estimate_weight(key) + estimate_weight(value)


class _Entry:
    __slots__ = ("value", "weight", "expiry")

    def __init__(self, value, weight: int, expiry: Optional[float]):
        self.value = value
        self.weight = weight
        self.expiry = expiry


class Cache:
    """Thread-safe weighted LRU cache.

    ``breaker``: a ``CircuitBreaker`` object, or a child name
    ("fielddata"/"request"/"in_flight") resolved against the INSTALLED
    breaker service at charge time (so tests that install() a sized
    service are honored).  ``max_weight=None`` disables weight eviction
    (the breaker still bounds residency).
    """

    def __init__(self, name: str, *,
                 max_weight: Optional[int] = None,
                 weigher: Optional[Callable] = None,
                 ttl_s: Optional[float] = None,
                 removal_listener: Optional[Callable] = None,
                 breaker=None,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.max_weight = max_weight
        self.weigher = weigher or _default_weigher
        self.ttl_s = ttl_s
        self.removal_listener = removal_listener
        self._breaker_ref = breaker
        self._clock = clock
        self._lock = threading.RLock()
        self._entries: "OrderedDict" = OrderedDict()
        self._weight = 0
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._rejections = 0

    # -- breaker plumbing --------------------------------------------------

    def _breaker(self):
        ref = self._breaker_ref
        if isinstance(ref, str):
            from opensearch_tpu.common.breakers import breaker_service
            return getattr(breaker_service(), ref)
        return ref

    def _charge(self, weight: int) -> bool:
        breaker = self._breaker()
        if breaker is None:
            return True
        try:
            breaker.add_estimate(weight, label=f"cache.{self.name}")
            return True
        except CircuitBreakingError:
            return False

    def _release(self, weight: int) -> None:
        breaker = self._breaker()
        if breaker is not None:
            breaker.release(weight)

    # -- internals (call with the lock held) -------------------------------

    def _remove(self, key, reason: str):
        entry = self._entries.pop(key, None)
        if entry is None:
            return
        self._weight -= entry.weight
        self._release(entry.weight)
        if reason == EVICTED:
            self._evictions += 1
            _metrics().counter(f"cache.{self.name}.evictions").inc()  # metric-name-ok: cache names are code-level identifiers
        if self.removal_listener is not None:
            self.removal_listener(key, entry.value, reason)

    def _evict_lru(self) -> bool:
        if not self._entries:
            return False
        key = next(iter(self._entries))
        self._remove(key, EVICTED)
        return True

    # -- public API --------------------------------------------------------

    def get(self, key, default=None):
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.expiry is not None \
                    and self._clock() >= entry.expiry:
                self._remove(key, EXPIRED)
                entry = None
            if entry is None:
                self._misses += 1
                _metrics().counter(f"cache.{self.name}.misses").inc()  # metric-name-ok: bounded set of cache names
                return default
            self._entries.move_to_end(key)
            self._hits += 1
            _metrics().counter(f"cache.{self.name}.hits").inc()  # metric-name-ok: bounded set of cache names
            return entry.value

    def get_or_load(self, key, loader: Callable):
        """Compute-if-absent.  The loader runs OUTSIDE the lock, so two
        racing callers may both compute (last write wins) — correct for
        derived data, which is all a cache may hold."""
        sentinel = object()
        value = self.get(key, sentinel)
        if value is not sentinel:
            return value
        value = loader()
        self.put(key, value)
        return value

    def put(self, key, value) -> bool:
        """Insert; returns False when the entry could not be admitted
        (single entry over max_weight, or the breaker refused even after
        evicting the whole cache)."""
        weight = int(self.weigher(key, value))
        with self._lock:
            self._remove(key, REPLACED)
            if self.max_weight is not None and weight > self.max_weight:
                self._rejections += 1
                return False
            # make room under the breaker by shedding our own LRU tail
            # before giving up — OTHER components' memory is not ours to
            # evict, so a still-tripping breaker means "don't cache"
            while not self._charge(weight):
                if not self._evict_lru():
                    self._rejections += 1
                    return False
            expiry = (self._clock() + self.ttl_s
                      if self.ttl_s is not None else None)
            self._entries[key] = _Entry(value, weight, expiry)
            self._weight += weight
            if self.max_weight is not None:
                while self._weight > self.max_weight:
                    self._evict_lru()
            return True

    def invalidate(self, key) -> None:
        with self._lock:
            self._remove(key, EXPLICIT)

    def invalidate_all(self) -> None:
        with self._lock:
            for key in list(self._entries):
                self._remove(key, EXPLICIT)

    def invalidate_if(self, pred: Callable) -> int:
        """Remove every entry where ``pred(key, value)`` is true;
        returns the number removed (targeted invalidation — e.g. one
        index's request-cache entries)."""
        with self._lock:
            doomed = [k for k, e in self._entries.items()
                      if pred(k, e.value)]
            for key in doomed:
                self._remove(key, EXPLICIT)
            return len(doomed)

    def set_max_weight(self, max_weight: Optional[int]) -> None:
        """Dynamic resize; shrinking evicts immediately."""
        with self._lock:
            self.max_weight = max_weight
            if max_weight is not None:
                while self._weight > max_weight:
                    if not self._evict_lru():
                        break

    def entries(self) -> list[tuple]:
        """Snapshot of (key, value, weight), LRU→MRU (stats walks)."""
        with self._lock:
            return [(k, e.value, e.weight)
                    for k, e in self._entries.items()]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def weight(self) -> int:
        return self._weight

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "memory_size_in_bytes": self._weight,
                    "hit_count": self._hits,
                    "miss_count": self._misses,
                    "evictions": self._evictions,
                    "rejections": self._rejections}


def attached_cache(owner, attr: str, *, name: str,
                   max_weight: Optional[int] = None,
                   weigher: Optional[Callable] = None,
                   breaker=None) -> Cache:
    """Get-or-create a bounded ``Cache`` stored as ``owner.<attr>`` —
    the sanctioned replacement for the ``getattr(obj, "_x_cache") or
    obj._x_cache = {}`` idiom.  A weakref finalizer releases the
    cache's breaker reservation when the owner dies, so per-segment /
    per-searcher caches can never leak accounted bytes."""
    cache = getattr(owner, attr, None)
    if cache is None:
        cache = Cache(name, max_weight=max_weight, weigher=weigher,
                      breaker=breaker)
        try:
            weakref.finalize(owner, cache.invalidate_all)
        except TypeError:
            pass                 # owner not weakref-able: best effort
        setattr(owner, attr, cache)
    return cache
