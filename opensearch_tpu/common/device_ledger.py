"""Device-resident memory & transfer observability: the staging ledger.

Until this module, nothing in the system could answer "what is on the
device, how many bytes, who staged it, and when was it last used" — the
fielddata breaker counted an *estimate* at segment-staging time and the
rest was assertion.  ROADMAP items 1 (continuous batching) and 5
(quantized device-resident indices at 10-100x corpus scale) both need a
measured device-memory budget line; GPUSparse (arxiv 2606.26441) treats
accelerator-resident index layout and transfer cost as first-class
engineering quantities.  This ledger makes them measurable here:

- **Residency ledger** — ALL device staging flows through it: every
  ``DeviceSegment`` array family (postings, impacts, doc values, live
  masks, nested blocks, ANN structures), the batched-msearch group
  arrays, and the mesh path's ``jax.device_put``.  Each entry records
  its owner (index/shard/segment/field/kind), exact staged nbytes, the
  staging tick, and per-owner dispatch count + last-dispatch tick.
  ``tools/check_device_staging.py`` (tier-1) rejects raw staging calls
  outside this module in ``index/``/``search/``/``parallel/``/``ops/``.
- **Transfer accounting** — host→device (stage) and device→host
  (fetch-back) byte/op/time counters, fed into the MetricsRegistry so
  ``/_metrics`` scrapes them and ``_nodes/stats`` reports them.
- **Compile registry** — per-kernel XLA program counts behind a
  version-tolerant ``_cache_size`` shim (jit's private introspection
  moved across jax versions; a missing attribute degrades to a counted
  ``unavailable`` instead of breaking the profiler).
- **Budget enforcement** — the first consumer: a dynamic
  ``device.memory.budget_bytes`` setting; when resident bytes exceed
  it, the least-recently-dispatched sealed segment stagings are
  unstaged (counted evictions, fielddata-breaker release).  Evicted
  scored term-bags degrade byte-identically to the host impact-table
  path (``TermBagPlan.host_topk`` — the PR-5 parity invariant); other
  plans restage on demand (counted restages).  This is the seed of
  ROADMAP item 5's host↔device paging.

The ledger is process-global (like the breaker service and the metrics
registry): in-process multi-node tests share one ledger, which is the
honest model — they also share one device.  Tests reset it via
``device_ledger().reset()``.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from typing import Callable, Optional

from opensearch_tpu.common.telemetry import metrics as _metrics

# entry kinds a DeviceSegment stages (the "array families" of the
# tentpole); batch/mesh/other producers add their own kinds
SEGMENT_KINDS = ("postings", "numeric", "ordinal", "vector", "geo",
                 "impacts", "live", "nested", "ann")


def host_footprint(seg, per_field: bool = False):
    """Host-side footprint of one ``Segment`` in bytes — THE source of
    truth for "how big is this segment" (replaces the hand-rolled
    estimate ``DeviceSegment`` used for its breaker charge and the
    ad-hoc doc-values math ``GET /_cat/fielddata`` did inline).

    Returns total bytes, or ``{(kind, field): bytes}`` with
    ``per_field=True``.  Pure numpy accounting; never touches jax.
    """
    out: dict[tuple, int] = {}

    def put(kind, field, *arrays):
        n = sum(int(getattr(a, "nbytes", 0)) for a in arrays
                if a is not None)
        if n:
            out[(kind, field)] = out.get((kind, field), 0) + n

    for name, pf in seg.postings.items():
        put("postings", name, pf.offsets, pf.doc_ids, pf.tfs,
            pf.pos_offsets, pf.positions, pf.doc_lens, pf.df, pf.present)
    for name, dv in seg.numeric_dv.items():
        put("numeric", name, dv.offsets, dv.values, dv.value_docs,
            dv.minv, dv.maxv, dv.exists)
    for name, dv in seg.ordinal_dv.items():
        put("ordinal", name, dv.offsets, dv.ords, dv.value_docs,
            dv.min_ord, dv.max_ord, dv.exists)
    for name, dv in seg.vector_dv.items():
        put("vector", name, dv.values, dv.exists)
    for name, dv in seg.geo_dv.items():
        put("geo", name, dv.offsets, dv.lats, dv.lons, dv.value_docs,
            dv.exists)
    if per_field:
        return out
    return sum(out.values())


class KernelCompileRegistry:
    """Per-kernel XLA compile/retrace registry: every jit entry point of
    the query path registers here, and ``counts()`` reads each one's
    live compiled-program count through a version-tolerant shim around
    jit's private ``_cache_size`` — generalizing the profiler's one-off
    delta so a jax upgrade that drops the introspection degrades the
    metric (counted ``unavailable``) instead of breaking the Profile
    API."""

    # default query-path kernels, resolved lazily (import cycles during
    # bootstrap are the same reason profile.py resolved them lazily)
    _DEFAULTS = (
        ("plan.run_topk", "opensearch_tpu.search.plan", "run_topk"),
        ("plan.run_full", "opensearch_tpu.search.plan", "run_full"),
        ("plan.topk_from_scores", "opensearch_tpu.search.plan",
         "topk_from_scores"),
        ("batch.batch_impact_union_topk", "opensearch_tpu.search.batch",
         "batch_impact_union_topk"),
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._kernels: dict[str, object] = {}
        self._defaults_loaded = False

    def register(self, name: str, fn) -> None:
        with self._lock:
            self._kernels[name] = fn

    def _ensure_defaults(self) -> None:
        if self._defaults_loaded:
            return
        import importlib
        loaded = {}
        for name, mod, attr in self._DEFAULTS:
            try:
                fn = getattr(importlib.import_module(mod), attr)
            except Exception:      # partial import cycle during bootstrap
                return             # retry on the next read
            loaded[name] = fn
        with self._lock:
            for name, fn in loaded.items():
                self._kernels.setdefault(name, fn)
            self._defaults_loaded = True

    @staticmethod
    def _cache_size_of(fn) -> Optional[int]:
        """The version-tolerant ``_cache_size`` shim: None when this jax
        doesn't expose compiled-program introspection for ``fn``."""
        size = getattr(fn, "_cache_size", None)
        if size is None:
            return None
        try:
            return int(size())
        except Exception:          # introspection changed shape again
            return None

    def counts(self) -> dict:
        """{"kernels": {name: programs}, "unavailable": n, "total": n}
        — kernels whose introspection is gone are listed under
        ``unavailable`` (counted, never raising)."""
        self._ensure_defaults()
        with self._lock:
            kernels = dict(self._kernels)
        out: dict[str, int] = {}
        unavailable = 0
        for name in sorted(kernels):
            n = self._cache_size_of(kernels[name])
            if n is None:
                unavailable += 1
            else:
                out[name] = n
        return {"kernels": out, "unavailable": unavailable,
                "total": sum(out.values())}

    def program_count(self) -> int:
        """Total live compiled programs across registered kernels (the
        profiler's ``xla_compiles`` delta source)."""
        return self.counts()["total"]


class _Group:
    """One staging owner's ledger entries — normally one DeviceSegment's
    whole array family set; also one batch-prep group or one mesh
    placement.  The group is the eviction unit: "unstage the
    least-recently-dispatched segment" means closing its group."""

    __slots__ = ("index", "shard", "segment", "entries", "staged_tick",
                 "dispatches", "last_dispatch_tick", "sealed",
                 "evict_cb", "evict_class", "_gid", "__weakref__")

    def __init__(self, index: str, shard, segment: str,
                 evict_cb: Optional[Callable] = None,
                 evict_class: str = "segment"):
        self.index = index
        self.shard = shard
        self.segment = segment
        self.entries: dict[tuple, int] = {}   # (kind, field, name) -> nbytes
        self.staged_tick = 0
        self.dispatches = 0
        self.last_dispatch_tick = 0
        self.sealed = False                   # unsealed groups never evict
        self.evict_cb = evict_cb              # None -> not evictable
        self.evict_class = evict_class        # "page" evicts before "segment"

    def nbytes(self) -> int:
        return sum(self.entries.values())

    def to_dict(self) -> dict:
        by_kind: dict[str, int] = {}
        for (kind, _f, _n), b in self.entries.items():
            by_kind[kind] = by_kind.get(kind, 0) + b
        return {"index": self.index, "shard": self.shard,
                "segment": self.segment, "bytes": self.nbytes(),
                "entries": len(self.entries),
                "by_kind": dict(sorted(by_kind.items())),
                "staged_tick": self.staged_tick,
                "dispatches": self.dispatches,
                "last_dispatch_tick": self.last_dispatch_tick,
                "evictable": self.evict_cb is not None and self.sealed}


class GroupCloser:
    """Keep one of these inside a cache entry that owns a staging group
    (dicts are not weakref-able, so ``tether`` can't watch them): when
    the entry is evicted or garbage collected, the sentinel closes the
    group and its ledger entries disappear with the staged arrays."""

    __slots__ = ("_ledger", "_group")

    def __init__(self, ledger: "DeviceResidencyLedger", group: "_Group"):
        self._ledger = ledger
        self._group = group

    def __del__(self):
        try:
            self._ledger.close_group(self._group)
        except Exception:
            pass


class DeviceResidencyLedger:
    """The device residency + transfer ledger (module docstring)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._groups: "dict[int, _Group]" = {}
        self._next_id = itertools.count(1)
        self._tick = itertools.count(1)
        self.budget_bytes: Optional[int] = None
        self.evictions = 0
        self.restages = 0
        self.host_fallbacks = 0
        self._evicted_bytes = 0
        self._transfers = {
            "stage": {"bytes": 0, "ops": 0, "seconds": 0.0},
            "fetch": {"bytes": 0, "ops": 0, "seconds": 0.0}}

    # -- group lifecycle ---------------------------------------------------

    def open_group(self, *, index: str = "-", shard=0, segment: str = "-",
                   evict: Optional[Callable] = None,
                   evict_class: str = "segment") -> _Group:
        """New (unsealed) staging group.  ``evict`` is the unstage
        callback the budget enforcer may call; groups without one are
        accounted but never evicted (batch/mesh stagings whose lifetime
        is owned by their caches).  ``evict_class="page"`` marks a
        cheap-to-restage group (the pager's quantized tables rebuild
        from host codec tables, not from a full segment restage) —
        budget enforcement spends pages before whole segments."""
        g = _Group(index, shard, segment, evict_cb=evict,
                   evict_class=evict_class)
        g.staged_tick = next(self._tick)
        gid = next(self._next_id)
        with self._lock:
            self._groups[gid] = g
        g._gid = gid  # type: ignore[attr-defined]
        return g

    def tether(self, owner, group: _Group) -> None:
        """Close ``group`` automatically when ``owner`` (a weakref-able
        object — e.g. a DeviceSegment) is garbage collected, so a
        refreshed-away staging cannot leak ledger entries."""
        weakref.finalize(owner, self._forget,
                         getattr(group, "_gid", -1))

    def _forget(self, gid: int) -> None:
        with self._lock:
            self._groups.pop(gid, None)

    def seal(self, group: _Group) -> None:
        """Mark the group fully staged — only sealed groups are eviction
        candidates (never unstage a segment mid-construction)."""
        group.sealed = True
        self._enforce(protect=group)

    def close_group(self, group: _Group) -> None:
        """Explicit removal (eviction or owner teardown)."""
        self._forget(getattr(group, "_gid", -1))

    # -- staging (H2D) -----------------------------------------------------

    def stage(self, group: Optional[_Group], host_array, *, kind: str,
              field: str = "", name: str = ""):
        """THE sanctioned host→device staging call: performs the
        transfer (``jnp.asarray``), times it, and records the entry
        under ``group`` with the exact staged nbytes.  Returns the
        device array."""
        import jax.numpy as jnp

        t0 = time.monotonic()
        out = jnp.asarray(host_array)      # staging-ok: the ledger itself
        dt = time.monotonic() - t0
        self._record(group, (kind, field, name),
                     int(getattr(host_array, "nbytes", None)
                         or out.nbytes), dt)
        return out

    def device_put(self, group: Optional[_Group], value, sharding=None,
                   *, kind: str = "mesh", field: str = "",
                   name: str = ""):
        """Sanctioned ``jax.device_put`` (the mesh placement path)."""
        import jax

        t0 = time.monotonic()
        out = jax.device_put(value, sharding)  # staging-ok: the ledger itself
        dt = time.monotonic() - t0
        self._record(group, (kind, field, name),
                     int(getattr(value, "nbytes", None) or 0), dt)
        return out

    def adopt(self, group: _Group, arrays, *, kind: str,
              field: str = "", name: str = "") -> None:
        """Account already-staged device arrays (ANN structures staged
        by their own builders) without re-performing the transfer."""
        total = 0
        stackk = [arrays]
        while stackk:
            v = stackk.pop()
            nb = getattr(v, "nbytes", None)
            if nb is not None:
                total += int(nb)
            elif isinstance(v, (tuple, list)):
                stackk.extend(v)
            elif isinstance(v, dict):
                stackk.extend(v.values())
        self._record(group, (kind, field, name), total, 0.0)

    def _record(self, group: Optional[_Group], key: tuple, nbytes: int,
                seconds: float) -> None:
        prev = 0
        with self._lock:
            if group is not None:
                prev = group.entries.get(key)
                group.entries[key] = nbytes
            t = self._transfers["stage"]
            t["bytes"] += nbytes
            t["ops"] += 1
            t["seconds"] += seconds
        _metrics().counter("device.transfer.stage.bytes").inc(nbytes)
        _metrics().counter("device.transfer.stage.ops").inc()
        if group is not None and prev is None and group.sealed:
            # post-seal additions (impacts/live staged lazily) can push
            # past the budget too
            self._enforce(protect=group)

    def drop(self, group: _Group, *, kind: str, field: str = "",
             name: str = "") -> None:
        """Remove one entry (its device array was dropped by the owning
        cache — e.g. a live-mask snapshot LRU'ing out)."""
        with self._lock:
            group.entries.pop((kind, field, name), None)

    # -- dispatch + fetch-back accounting ----------------------------------

    def record_dispatch(self, group: Optional[_Group]) -> None:
        """One device program consumed this group's arrays — the LRU
        signal budget eviction orders by."""
        if group is None:
            return
        with self._lock:
            group.dispatches += 1
            group.last_dispatch_tick = next(self._tick)

    def record_fetch(self, nbytes: int, seconds: float) -> None:
        """Device→host result readback (the sync regions of the query
        path and the mesh merge)."""
        with self._lock:
            t = self._transfers["fetch"]
            t["bytes"] += int(nbytes)
            t["ops"] += 1
            t["seconds"] += seconds
        _metrics().counter("device.transfer.fetch.bytes").inc(int(nbytes))
        _metrics().counter("device.transfer.fetch.ops").inc()

    def record_restage(self) -> None:
        """A previously evicted segment was staged again (demand
        paging's fault counter)."""
        with self._lock:
            self.restages += 1
        _metrics().counter("device.restages").inc()

    def record_host_fallback(self) -> None:
        """An evicted segment scored on the host impact tables instead
        of restaging (the byte-identical degradation path)."""
        with self._lock:
            self.host_fallbacks += 1
        _metrics().counter("device.host_fallback").inc()

    # -- budget enforcement ------------------------------------------------

    def set_budget(self, budget_bytes: Optional[int]) -> None:
        """Dynamic ``device.memory.budget_bytes`` consumer; 0/None =
        unlimited.  Applies immediately."""
        b = int(budget_bytes) if budget_bytes else 0
        self.budget_bytes = b if b > 0 else None
        self._enforce()

    def _enforce(self, protect: Optional[_Group] = None) -> None:
        """Unstage least-recently-dispatched sealed groups until
        resident bytes fit the budget.  ``protect`` (the group just
        staged) is never evicted — evicting the staging you are in the
        middle of serving would livelock demand paging."""
        budget = self.budget_bytes
        if budget is None:
            return
        while True:
            with self._lock:
                resident = sum(g.nbytes() for g in self._groups.values())
                if resident <= budget:
                    return
                victims = [g for g in self._groups.values()
                           if g.sealed and g.evict_cb is not None
                           and g is not protect]
                if not victims:
                    return          # nothing evictable: stay over budget
                # cheap-to-restage pages go before whole segments
                # (a page rebuilds from host codec tables; a segment
                # eviction forces host fallback or a full restage);
                # within a class, least-recently-dispatched first
                victim = min(victims,
                             key=lambda g: (g.evict_class != "page",
                                            g.last_dispatch_tick,
                                            g.staged_tick))
                freed = victim.nbytes()
                self.evictions += 1
                self._evicted_bytes += freed
                cb = victim.evict_cb
                victim.evict_cb = None    # never evict twice
            _metrics().counter("device.evictions").inc()
            _metrics().counter("device.evicted.bytes").inc(freed)
            try:
                cb()                      # releases the breaker charge
            finally:
                self.close_group(victim)

    # -- readout -----------------------------------------------------------

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(g.nbytes() for g in self._groups.values())

    def transfer_snapshot(self) -> tuple[int, int]:
        """(stage_bytes, fetch_bytes) monotonic totals — per-query
        attribution takes deltas (the insights transfer_bytes field)."""
        with self._lock:
            return (self._transfers["stage"]["bytes"],
                    self._transfers["fetch"]["bytes"])

    def device_footprint(self, seg) -> int:
        """Currently staged bytes of one ``Segment`` (0 when it is not
        device-resident)."""
        dseg = getattr(seg, "_device", None)
        group = getattr(dseg, "_ledger_group", None)
        if group is None:
            return 0
        with self._lock:
            return group.nbytes()

    def stats(self) -> dict:
        """The ``_nodes/stats`` ``device`` section body: residency
        rollups per index, transfer counters, budget/eviction
        accounting, and the per-kernel compile registry."""
        with self._lock:
            groups = list(self._groups.values())
            transfers = {
                side: {"bytes": t["bytes"], "ops": t["ops"],
                       "time_ms": round(t["seconds"] * 1000.0, 3)}
                for side, t in self._transfers.items()}
            budget = self.budget_bytes
            ev, evb = self.evictions, self._evicted_bytes
            rs, hf = self.restages, self.host_fallbacks
        per_index: dict[str, dict] = {}
        resident = 0
        dispatches = 0
        for g in groups:
            b = g.nbytes()
            resident += b
            dispatches += g.dispatches
            ix = per_index.setdefault(
                g.index, {"bytes": 0, "segments": 0, "dispatches": 0})
            ix["bytes"] += b
            ix["segments"] += 1
            ix["dispatches"] += g.dispatches
        return {
            "resident_bytes": resident,
            "resident_segments": len(groups),
            "dispatches": dispatches,
            "budget": {
                "budget_bytes": budget or 0,
                "evictions": ev,
                "evicted_bytes": evb,
                "restages": rs,
                "host_fallbacks": hf,
            },
            "transfers": transfers,
            "pager": device_pager().stats(),
            "indices": dict(sorted(per_index.items())),
            "compile_registry": kernel_registry().counts(),
            "backend": _backend_memory_stats(),
        }

    def segments(self) -> list[dict]:
        """Per-group detail rows (debug surface; `_cat/segments` reads
        footprints through ``device_footprint`` instead)."""
        with self._lock:
            groups = sorted(self._groups.values(),
                            key=lambda g: (g.index, str(g.shard),
                                           g.segment))
        return [g.to_dict() for g in groups]

    def prometheus_text(self) -> str:
        """Gauge exposition for the scrape surface (counters already
        flow through the MetricsRegistry)."""
        s = self.stats()
        lines = [
            "# HELP opensearch_tpu_device_resident_bytes "
            "Device-resident ledger bytes",
            "# TYPE opensearch_tpu_device_resident_bytes gauge",
            f"opensearch_tpu_device_resident_bytes {s['resident_bytes']}",
            "# HELP opensearch_tpu_device_budget_bytes "
            "Configured device memory budget (0 = unlimited)",
            "# TYPE opensearch_tpu_device_budget_bytes gauge",
            "opensearch_tpu_device_budget_bytes "
            f"{s['budget']['budget_bytes']}",
            "# HELP opensearch_tpu_device_resident_segments "
            "Device-resident staging groups",
            "# TYPE opensearch_tpu_device_resident_segments gauge",
            "opensearch_tpu_device_resident_segments "
            f"{s['resident_segments']}",
            "# HELP opensearch_tpu_device_pager_resident_pages "
            "Quantized-index pager resident pages",
            "# TYPE opensearch_tpu_device_pager_resident_pages gauge",
            "opensearch_tpu_device_pager_resident_pages "
            f"{s['pager']['resident_pages']}",
            "# HELP opensearch_tpu_device_pager_capacity_pages "
            "Quantized-index pager page capacity (-1 = unlimited)",
            "# TYPE opensearch_tpu_device_pager_capacity_pages gauge",
            "opensearch_tpu_device_pager_capacity_pages "
            f"{s['pager']['capacity_pages'] if s['pager']['capacity_pages'] is not None else -1}",
        ]
        lines.append(
            "# HELP opensearch_tpu_device_index_resident_bytes "
            "Device-resident bytes per index")
        lines.append(
            "# TYPE opensearch_tpu_device_index_resident_bytes gauge")
        for ix, row in s["indices"].items():
            ixv = (str(ix).replace("\\", "\\\\").replace('"', '\\"')
                   .replace("\n", "\\n"))
            lines.append(
                f'opensearch_tpu_device_index_resident_bytes'
                f'{{index="{ixv}"}} {row["bytes"]}')  # label-ok: bounded by index count
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Test hook: forget all groups and zero the counters (the
        staged arrays themselves stay owned by their segments)."""
        with self._lock:
            self._groups.clear()
            self.budget_bytes = None
            self.evictions = self.restages = self.host_fallbacks = 0
            self._evicted_bytes = 0
            for t in self._transfers.values():
                t["bytes"] = t["ops"] = 0
                t["seconds"] = 0.0
        device_pager().reset()


class _PageEntry:
    """One pager residency unit: the staged device arrays of one
    quantized (segment, field, avgdl) table set, accounted in fixed-size
    pages."""

    __slots__ = ("key", "arrays", "group", "nbytes", "pages",
                 "last_use_tick")

    def __init__(self, key, arrays, group, nbytes, pages, tick):
        self.key = key
        self.arrays = arrays
        self.group = group
        self.nbytes = nbytes
        self.pages = pages
        self.last_use_tick = tick


class DevicePager:
    """Host↔device pager for quantized segment groups (ROADMAP item 2's
    paging half).

    Quantized table sets (index/codec.py) are staged as fixed-size
    *pages* under the same ``device.memory.budget_bytes`` the ledger
    enforces: capacity is ``budget_bytes // page_bytes``; an ``acquire``
    that doesn't fit evicts the least-recently-used resident entry
    first (pager-level LRU — finer-grained and cheaper to restage than
    whole-segment ledger eviction, because a quantized page rebuilds
    from the host codec tables, not from a full segment restage).
    ``prefetch`` stages ahead of the dispatch loop but only into FREE
    pages — the prefetch oracle (per-term block-max score bounds, see
    ``TermBagPlan.prefetch_quantized``) ranks what is worth staging; it
    never thrashes demand-paged residents.

    Every staging flows through the owning ledger, so pager pages also
    show up in residency/transfer accounting, and the ledger's own
    budget enforcement can evict a pager group like any other sealed
    group (the pager is told via the evict callback and keeps its book
    straight).  Miss/evict/prefetch counters feed ``_nodes/stats``
    ``device.pager`` and ``/_metrics``.
    """

    DEFAULT_PAGE_BYTES = 1 << 20

    def __init__(self, ledger: DeviceResidencyLedger):
        self._led = ledger
        self._lock = threading.Lock()
        self.page_bytes = self.DEFAULT_PAGE_BYTES
        self._entries: dict[tuple, _PageEntry] = {}
        self._tick = itertools.count(1)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.evicted_pages = 0
        self.prefetches = 0

    def set_page_bytes(self, n) -> None:
        """Dynamic ``device.pager.page_bytes`` consumer (0/None keeps
        the default)."""
        n = int(n) if n else 0
        self.page_bytes = n if n > 0 else self.DEFAULT_PAGE_BYTES

    def capacity_pages(self):
        """None = unlimited (no device budget configured)."""
        budget = self._led.budget_bytes
        if budget is None:
            return None
        return max(1, budget // self.page_bytes)

    def resident_pages(self) -> int:
        with self._lock:
            return sum(e.pages for e in self._entries.values())

    def _pages_of(self, nbytes: int) -> int:
        return max(1, -(-int(nbytes) // self.page_bytes))

    def acquire(self, key, loader, *, index: str = "-", shard=0,
                segment: str = "-"):
        """Resident arrays for ``key``, staging (and evicting LRU pages
        to fit) on miss.  ``loader()`` returns the host payload as a
        list of ``(name, kind, np_array)``; the staged dict is keyed by
        name."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self.hits += 1
                e.last_use_tick = next(self._tick)
                group = e.group
                arrays = e.arrays
        if e is not None:
            self._led.record_dispatch(group)
            _metrics().counter("device.pager.hits").inc()
            return arrays
        with self._lock:
            self.misses += 1
        _metrics().counter("device.pager.misses").inc()
        return self._stage(key, loader(), index=index, shard=shard,
                           segment=segment, prefetched=False)

    def prefetch(self, key, loader, nbytes_hint: int, *,
                 index: str = "-", shard=0, segment: str = "-") -> bool:
        """Stage ``key`` ahead of demand IF it fits in free pages —
        prefetch never evicts a resident entry, so a bad oracle ranking
        costs nothing but spare capacity.  Returns True when staged."""
        cap = self.capacity_pages()
        need = self._pages_of(nbytes_hint)
        with self._lock:
            if key in self._entries:
                return False
            if cap is not None:
                free = cap - sum(e.pages for e in self._entries.values())
                if free < need:
                    return False
        self._stage(key, loader(), index=index, shard=shard,
                    segment=segment, prefetched=True)
        return True

    def _stage(self, key, items, *, index, shard, segment, prefetched):
        field = key[3] if len(key) > 3 else ""
        cb = lambda: self._on_ledger_evict(key)  # noqa: E731
        group = self._led.open_group(index=index, shard=shard,
                                     segment=segment, evict=cb,
                                     evict_class="page")
        arrays = {}
        nbytes = 0
        for name, kind, arr in items:
            arrays[name] = self._led.stage(group, arr, kind=kind,
                                           field=field, name=name)
            nbytes += int(getattr(arr, "nbytes", 0))
        pages = self._pages_of(nbytes)
        entry = _PageEntry(key, arrays, group, nbytes, pages,
                           next(self._tick))
        evict_keys = []
        with self._lock:
            prior = self._entries.get(key)   # benign load race: keep ours
            self._entries[key] = entry
            cap = self.capacity_pages()
            if cap is not None:
                while sum(e.pages
                          for e in self._entries.values()) > cap:
                    victims = [e for e in self._entries.values()
                               if e is not entry]
                    if not victims:
                        break                # one entry over capacity
                    v = min(victims, key=lambda e: e.last_use_tick)
                    del self._entries[v.key]
                    self.evictions += 1
                    self.evicted_pages += v.pages
                    evict_keys.append(v)
            if prefetched:
                self.prefetches += 1
        if prior is not None:
            self._led.close_group(prior.group)
        for v in evict_keys:
            _metrics().counter("device.pager.evictions").inc()
            self._led.close_group(v.group)
        if prefetched:
            _metrics().counter("device.pager.prefetches").inc()
        # seal AFTER the pager's own eviction pass so ledger budget
        # enforcement sees the post-eviction footprint
        self._led.seal(group)
        return arrays

    def _on_ledger_evict(self, key) -> None:
        """The owning ledger's budget enforcement chose this pager group
        as its LRU victim — drop the entry and count it here too."""
        with self._lock:
            e = self._entries.pop(key, None)
            if e is None:
                return
            self.evictions += 1
            self.evicted_pages += e.pages
        _metrics().counter("device.pager.evictions").inc()

    def invalidate(self, key) -> None:
        """Owner teardown (segment merged away / GC'd)."""
        with self._lock:
            e = self._entries.pop(key, None)
        if e is not None:
            self._led.close_group(e.group)

    def stats(self) -> dict:
        with self._lock:
            resident = sum(e.pages for e in self._entries.values())
            resident_bytes = sum(e.nbytes
                                 for e in self._entries.values())
            out = {
                "page_bytes": self.page_bytes,
                "capacity_pages": self.capacity_pages(),
                "resident_pages": resident,
                "resident_entries": len(self._entries),
                "resident_bytes": resident_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "evicted_pages": self.evicted_pages,
                "prefetches": self.prefetches,
            }
        return out

    def reset(self) -> None:
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
            self.hits = self.misses = self.evictions = 0
            self.evicted_pages = self.prefetches = 0
            self.page_bytes = self.DEFAULT_PAGE_BYTES
        for e in entries:
            self._led.close_group(e.group)


def _backend_memory_stats() -> dict:
    """``jax`` device ``memory_stats()`` where the backend provides it
    (TPU/GPU do; CPU returns None) — the allocator's own view next to
    the ledger's."""
    try:
        import jax
        dev = jax.devices()[0]
        raw = dev.memory_stats()
        if not raw:
            return {"available": False, "platform": dev.platform}
        keep = {k: int(v) for k, v in raw.items()
                if isinstance(v, (int, float)) and (
                    "bytes" in k or "allocs" in k)}
        return {"available": True, "platform": dev.platform, **keep}
    except Exception:
        return {"available": False}


_ledger = DeviceResidencyLedger()
_registry = KernelCompileRegistry()
_pager = DevicePager(_ledger)


def device_ledger() -> DeviceResidencyLedger:
    return _ledger


def device_pager() -> DevicePager:
    return _pager


def kernel_registry() -> KernelCompileRegistry:
    return _registry
