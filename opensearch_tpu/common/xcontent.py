"""Pluggable content formats: JSON, YAML, CBOR.

Analog of the reference's x-content abstraction (ref libs/x-content/src/
main/java/org/opensearch/common/xcontent/XContentType.java:38 — JSON,
SMILE, YAML, CBOR): request bodies negotiate via Content-Type, responses
via Accept or the ``format`` query param.  SMILE is not implemented
(niche binary JSON; CBOR covers the binary use case) and is rejected
with a clear 406.

The CBOR codec is self-contained (RFC 8949 subset: the definite-length
major types JSON can express — ints, floats, text, bytes, arrays, maps,
bool/null) — no third-party dependency is available in this image.
"""

from __future__ import annotations

import json
import struct
from typing import Any

from opensearch_tpu.common.errors import OpenSearchTpuError, ParsingError


class UnsupportedMediaTypeError(OpenSearchTpuError):
    status = 406


# -- CBOR (RFC 8949 subset) --------------------------------------------------

def _cbor_head(major: int, arg: int) -> bytes:
    if arg < 24:
        return bytes([(major << 5) | arg])
    for ai, fmt in ((24, ">B"), (25, ">H"), (26, ">I"), (27, ">Q")):
        if arg < (1 << (8 * struct.calcsize(fmt))):
            return bytes([(major << 5) | ai]) + struct.pack(fmt, arg)
    raise ValueError("integer too large for CBOR")


def cbor_dumps(obj: Any) -> bytes:
    out = bytearray()

    def enc(v):
        if v is None:
            out.append(0xF6)
        elif v is True:
            out.append(0xF5)
        elif v is False:
            out.append(0xF4)
        elif isinstance(v, int):
            if v >= 0:
                out.extend(_cbor_head(0, v))
            else:
                out.extend(_cbor_head(1, -1 - v))
        elif isinstance(v, float):
            out.append(0xFB)
            out.extend(struct.pack(">d", v))
        elif isinstance(v, bytes):
            out.extend(_cbor_head(2, len(v)))
            out.extend(v)
        elif isinstance(v, str):
            b = v.encode()
            out.extend(_cbor_head(3, len(b)))
            out.extend(b)
        elif isinstance(v, (list, tuple)):
            out.extend(_cbor_head(4, len(v)))
            for x in v:
                enc(x)
        elif isinstance(v, dict):
            out.extend(_cbor_head(5, len(v)))
            for k, x in v.items():
                enc(str(k))
                enc(x)
        else:
            raise ParsingError(
                f"cannot encode [{type(v).__name__}] as CBOR")

    enc(obj)
    return bytes(out)


def cbor_loads(data: bytes) -> Any:
    pos = 0
    depth = 0

    def need(n):
        nonlocal pos
        if pos + n > len(data):
            raise ParsingError("truncated CBOR input")
        chunk = data[pos:pos + n]
        pos += n
        return chunk

    def arg(ai):
        if ai < 24:
            return ai
        if ai in (24, 25, 26, 27):
            fmt = {24: ">B", 25: ">H", 26: ">I", 27: ">Q"}[ai]
            return struct.unpack(fmt, need(struct.calcsize(fmt)))[0]
        raise ParsingError(
            f"unsupported CBOR additional info [{ai}] "
            "(indefinite lengths not supported)")

    def dec():
        nonlocal depth
        depth += 1
        if depth > 256:                  # bound before RecursionError
            raise ParsingError("CBOR input nested too deeply")
        try:
            return _dec_inner()
        finally:
            depth -= 1

    def _dec_map(n):
        out = {}
        for _ in range(n):
            k = dec()
            if not isinstance(k, str):
                # JSON-compatible documents only (the reference's CBOR
                # parser surfaces into the same Map<String,Object>)
                raise ParsingError(
                    f"CBOR map keys must be text strings, got "
                    f"[{type(k).__name__}]")
            out[k] = dec()
        return out

    def _bounded(n):
        # every element takes >= 1 byte: a declared count beyond the
        # remaining input is malformed, not a reason to spin
        if n > len(data) - pos:
            raise ParsingError(
                f"CBOR container length [{n}] exceeds input size")
        return n

    def _dec_inner():
        head = need(1)[0]
        major, ai = head >> 5, head & 0x1F
        if major == 0:
            return arg(ai)
        if major == 1:
            return -1 - arg(ai)
        if major == 2:
            return bytes(need(arg(ai)))
        if major == 3:
            try:
                return need(arg(ai)).decode()
            except UnicodeDecodeError as e:
                raise ParsingError(f"invalid UTF-8 in CBOR text: {e}")
        if major == 4:
            return [dec() for _ in range(_bounded(arg(ai)))]
        if major == 5:
            return _dec_map(_bounded(arg(ai)))
        if major == 6:                   # tag: decode and drop, like
            arg(ai)                      # most lenient decoders
            return dec()
        # major 7: simple values / floats
        if ai == 20:
            return False
        if ai == 21:
            return True
        if ai in (22, 23):
            return None
        if ai == 25:                     # half float
            h = struct.unpack(">H", need(2))[0]
            sign = -1.0 if h & 0x8000 else 1.0
            exp, frac = (h >> 10) & 0x1F, h & 0x3FF
            if exp == 0:
                return sign * frac * 2.0 ** -24
            if exp == 31:
                return sign * (float("inf") if frac == 0
                               else float("nan"))
            return sign * (1 + frac / 1024.0) * 2.0 ** (exp - 15)
        if ai == 26:
            return struct.unpack(">f", need(4))[0]
        if ai == 27:
            return struct.unpack(">d", need(8))[0]
        raise ParsingError(f"unsupported CBOR simple value [{ai}]")

    v = dec()
    if pos != len(data):
        raise ParsingError("trailing bytes after CBOR value")
    return v


# -- negotiation -------------------------------------------------------------

_CT_JSON = "application/json"
_CT_YAML = "application/yaml"
_CT_CBOR = "application/cbor"
_CT_SMILE = "application/smile"


def _media_type(header: str) -> str:
    return (header or "").split(";")[0].strip().lower()


def from_bytes(data: bytes, content_type: str = "") -> Any:
    """Parse a request body per its Content-Type (JSON when absent)."""
    mt = _media_type(content_type)
    if mt == _CT_SMILE:
        raise UnsupportedMediaTypeError(
            "Content-Type [application/smile] is not supported — use "
            "json, yaml, or cbor")
    if mt == _CT_CBOR:
        return cbor_loads(data)
    if mt in (_CT_YAML, "text/yaml", "application/x-yaml"):
        import yaml
        try:
            return yaml.safe_load(data)
        except yaml.YAMLError as e:
            raise ParsingError(f"request body is not valid YAML: {e}")
    try:
        return json.loads(data)
    except json.JSONDecodeError as e:
        raise ParsingError(f"request body is not valid JSON: {e}")


def to_bytes(payload: Any, accept: str = "",
             format_param: str = "") -> tuple[bytes, str]:
    """Serialize a response per ``format`` param (wins, like the
    reference's ``?format=yaml``) or Accept header.  Returns
    (body, content-type)."""
    fmt = (format_param or "").lower() or _media_type(accept)
    if fmt in ("cbor", _CT_CBOR):
        return cbor_dumps(payload), _CT_CBOR
    if fmt in ("yaml", _CT_YAML, "text/yaml", "application/x-yaml"):
        import yaml
        return (yaml.safe_dump(payload, sort_keys=False,
                               default_flow_style=False).encode(),
                f"{_CT_YAML}; charset=UTF-8")
    if fmt in ("smile", _CT_SMILE):
        raise UnsupportedMediaTypeError(
            "format [smile] is not supported — use json, yaml, or cbor")
    return ((json.dumps(payload) + "\n").encode(),
            f"{_CT_JSON}; charset=UTF-8")
