"""Hierarchical circuit breakers: memory budgets that reject work
instead of dying.

Analog of the reference's HierarchyCircuitBreakerService (ref
indices/breaker/HierarchyCircuitBreakerService.java:1,
common/breaker/).  Children account independent concerns and a parent
caps their sum:

- ``fielddata`` — device-staged segment columns (the HBM budget: every
  DeviceSegment's arrays are charged on staging and released when the
  staging is dropped);
- ``request``   — per-request transient host memory (scroll cursor
  materialization, agg partial buffers);
- ``in_flight_requests`` — raw HTTP/transport payload bytes being
  parsed.

Tripping raises ``CircuitBreakingError`` (429, like the reference's
too_many_requests mapping) with the would-be usage in the message.
"""

from __future__ import annotations

import threading
from typing import Optional

from opensearch_tpu.common.errors import OpenSearchTpuError


class CircuitBreakingError(OpenSearchTpuError):
    status = 429


class CircuitBreaker:
    def __init__(self, name: str, limit: int, parent: "ParentBreaker"):
        self.name = name
        self.limit = int(limit)
        self.parent = parent
        self.used = 0
        self.trip_count = 0
        self._lock = threading.Lock()

    def add_estimate(self, bytes_: int, label: str = "<unknown>") -> None:
        """Reserve ``bytes_`` against this breaker + the parent; raises
        CircuitBreakingError without reserving when either would trip."""
        bytes_ = int(bytes_)
        if bytes_ <= 0:
            return
        with self._lock:
            new = self.used + bytes_
            if new > self.limit:
                self.trip_count += 1
                raise CircuitBreakingError(
                    f"[{self.name}] Data too large, data for [{label}] "
                    f"would be [{new}b], which is larger than the limit "
                    f"of [{self.limit}b]")
            self.parent.check(bytes_, self.name, label)
            self.used = new

    def release(self, bytes_: int) -> None:
        bytes_ = int(bytes_)
        if bytes_ <= 0:
            return
        with self._lock:
            self.used = max(0, self.used - bytes_)

    def stats(self) -> dict:
        return {"limit_size_in_bytes": self.limit,
                "estimated_size_in_bytes": self.used,
                "tripped": self.trip_count}


class ParentBreaker:
    def __init__(self, limit: int):
        self.limit = int(limit)
        self.trip_count = 0
        self._children: list[CircuitBreaker] = []
        self._lock = threading.Lock()

    def check(self, extra: int, child: str, label: str) -> None:
        with self._lock:
            total = sum(c.used for c in self._children) + extra
            if total > self.limit:
                self.trip_count += 1
                raise CircuitBreakingError(
                    f"[parent] Data too large, data for [{label}] (child "
                    f"[{child}]) would be [{total}b], which is larger "
                    f"than the limit of [{self.limit}b]")


class CircuitBreakerService:
    """The node's breaker registry.  Limits are plain byte counts taken
    from settings (defaults sized for a dev host; production tunes them
    like the reference's indices.breaker.* settings)."""

    GB = 1 << 30

    def __init__(self, settings: Optional[dict] = None):
        s = settings or {}
        parent_limit = int(s.get("breaker.total.limit", 12 * self.GB))
        self.parent = ParentBreaker(parent_limit)
        self.fielddata = self._child(
            "fielddata", int(s.get("breaker.fielddata.limit",
                                   8 * self.GB)))
        self.request = self._child(
            "request", int(s.get("breaker.request.limit", 4 * self.GB)))
        self.in_flight = self._child(
            "in_flight_requests",
            int(s.get("breaker.inflight.limit", 2 * self.GB)))

    def _child(self, name: str, limit: int) -> CircuitBreaker:
        b = CircuitBreaker(name, limit, self.parent)
        self.parent._children.append(b)
        return b

    def stats(self) -> dict:
        out = {b.name: b.stats()
               for b in (self.fielddata, self.request, self.in_flight)}
        out["parent"] = {
            "limit_size_in_bytes": self.parent.limit,
            "estimated_size_in_bytes": sum(
                b.used for b in self.parent._children),
            "tripped": self.parent.trip_count}
        return out


# Node-global default service: library users (engine/searcher) account
# against this unless a node installs its own configured instance.
_default = CircuitBreakerService()


def breaker_service() -> CircuitBreakerService:
    return _default


def install(service: CircuitBreakerService) -> None:
    global _default
    _default = service
