"""opensearch_tpu — a TPU-native distributed search engine.

A ground-up re-design of the OpenSearch capability surface (reference:
/root/reference, Apache-2.0 OpenSearch core 3.0.0-dev) for TPU hardware:

- The data plane is array-oriented: an index shard is a set of immutable,
  blocked, HBM-resident arrays (CSR postings with precomputed BM25 impacts,
  doc-value columns, dense vectors).  A query compiles to a jit'd
  gather -> scatter-add -> top_k program on device (eager sparse scoring in
  the style of BM25S, arXiv:2407.03618) instead of Lucene's branchy
  doc-at-a-time WAND loop (reference:
  server/src/main/java/org/opensearch/search/internal/ContextIndexSearcher.java:318).
- The control plane (cluster state, routing, translog, recovery, REST) is
  host-side Python, mirroring the reference's layer split of transport (L5)
  under actions (L6) (see SURVEY.md §1).
- Distribution is jax.sharding over a device Mesh: cross-shard top-k /
  aggregation merge is an ICI all-gather + on-device reduce rather than the
  reference's hand-rolled scatter-gather over Netty RPC
  (action/search/AbstractSearchAsyncAction.java:223).
"""

from opensearch_tpu.version import __version__  # noqa: F401
