"""Identity: internal users, basic auth, coarse role enforcement.

Analog of the reference's identity subsystem (ref server/src/main/java/
org/opensearch/identity/IdentityService.java:23 + the internal-users
model of the security plugin).  Scope matches the in-core feature, not
the full security plugin: an internal user store (PBKDF2-hashed
passwords, persisted), HTTP Basic authentication, and two built-in
roles — ``admin`` (everything) and ``readonly`` (GET plus search/count
POSTs).  Disabled until ``identity.enabled`` is set, like the
reference's feature-flagged identity.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import secrets
import threading
from typing import Optional

from opensearch_tpu.common.errors import (IllegalArgumentError,
                                          OpenSearchTpuError)


class AuthenticationError(OpenSearchTpuError):
    status = 401


class AuthorizationError(OpenSearchTpuError):
    status = 403


ROLES = ("admin", "readonly")
# Handlers a readonly principal may hit beyond plain GET/HEAD: the
# search-shaped POSTs plus releasing its own scroll/PIT contexts.
# Authorization keys on the MATCHED ROUTE's handler, never on the raw
# path — substring/suffix path checks are bypassable with crafted
# document ids like POST /idx/_doc/_search (review finding, reproduced)
READONLY_HANDLERS = frozenset({
    "h_search", "h_msearch", "h_count", "h_field_caps", "h_analyze",
    "h_termvectors", "h_rank_eval", "h_mget", "h_scroll_next",
    "h_scroll_clear", "h_scroll_clear_all", "h_pit_open", "h_pit_close",
})
# security APIs require admin even for reads (user enumeration hands an
# attacker the exact accounts to target)
_ADMIN_ONLY_PREFIX = "h_security_"


def _hash(password: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", password.encode(), salt,
                               50_000)


class IdentityService:
    def __init__(self, data_path: str):
        self.path = os.path.join(data_path, "security", "users.json")
        self._lock = threading.RLock()
        self.enabled = False
        self._users: dict[str, dict] = {}
        # name -> sha256(salt || password) of an ALREADY PBKDF2-verified
        # credential: the slow KDF runs once per (user, password), not
        # per request (the reference realms cache verified creds the
        # same way); invalidated on any user mutation
        self._verified: dict[str, bytes] = {}
        if os.path.exists(self.path):
            with open(self.path) as f:
                self._users = json.load(f)

    def _persist(self):
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._users, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- user management --------------------------------------------------

    def put_user(self, name: str, password: str,
                 roles: Optional[list] = None):
        """``roles=None`` preserves an existing user's roles (password
        rotation must not silently demote — demoting the sole admin
        would lock user management out permanently); new users default
        to readonly."""
        if not name or "/" in name or ":" in name:
            raise IllegalArgumentError(f"invalid username [{name}]")
        if not password or len(password) < 6:
            raise IllegalArgumentError(
                "password must be at least 6 characters")
        if roles is None:
            existing = self._users.get(name)
            roles = existing["roles"] if existing else ["readonly"]
        bad = [r for r in roles if r not in ROLES]
        if bad or not roles:
            raise IllegalArgumentError(
                f"invalid roles {bad or roles} — supported: "
                f"{list(ROLES)}")
        salt = secrets.token_bytes(16)
        with self._lock:
            created = name not in self._users
            self._users[name] = {
                "salt": salt.hex(),
                "hash": _hash(password, salt).hex(),
                "roles": sorted(set(roles))}
            self._verified.pop(name, None)
            self._persist()
            return created

    def delete_user(self, name: str) -> bool:
        with self._lock:
            existed = self._users.pop(name, None) is not None
            self._verified.pop(name, None)
            if existed:
                self._persist()
            return existed

    def list_users(self) -> dict:
        with self._lock:
            return {n: {"roles": u["roles"]}
                    for n, u in sorted(self._users.items())}

    # -- enforcement ------------------------------------------------------

    def authenticate(self, authorization: str) -> dict:
        """Basic-auth header -> user record; constant-time compare."""
        if not authorization or not authorization.startswith("Basic "):
            raise AuthenticationError("missing authentication credentials")
        try:
            raw = base64.b64decode(authorization[6:]).decode()
            name, _, password = raw.partition(":")
        except Exception:  # noqa: BLE001 — any malformed header is a 401
            raise AuthenticationError("invalid basic auth header")
        user = self._users.get(name)
        if user is None:
            # pay the full PBKDF2 cost for unknown users too, or response
            # timing enumerates valid account names
            _hash(password, b"\x00" * 16)
            raise AuthenticationError(
                f"authentication failed for [{name}]")
        salt = bytes.fromhex(user["salt"])
        fast = hashlib.sha256(salt + password.encode()).digest()
        cached = self._verified.get(name)
        if cached is not None and hmac.compare_digest(cached, fast):
            return {"name": name, "roles": user["roles"]}
        want = bytes.fromhex(user["hash"])
        got = _hash(password, salt)
        if not hmac.compare_digest(want, got):
            raise AuthenticationError(
                f"authentication failed for [{name}]")
        with self._lock:
            self._verified[name] = fast
        return {"name": name, "roles": user["roles"]}

    def authorize(self, principal: dict | None, method: str, path: str,
                  handler: str):
        """Route-level authorization: ``handler`` is the matched route's
        handler name (the action identity), resolved AFTER routing so
        path tricks can't reclassify an action."""
        if principal is None:
            return
        if handler.startswith(_ADMIN_ONLY_PREFIX):
            if "admin" not in principal["roles"]:
                raise AuthorizationError(
                    f"no permissions for [{handler.removeprefix('h_')}] "
                    f"and user [{principal['name']}]")
            return
        if "admin" in principal["roles"]:
            return
        if method in ("GET", "HEAD") or handler in READONLY_HANDLERS:
            return
        raise AuthorizationError(
            f"no permissions for [{method} {path}] and user "
            f"[{principal['name']}]")

    def check(self, method: str, path: str,
              authorization: str) -> dict | None:
        """Authentication gate for one request (authorization happens
        per matched route via ``authorize``); no-op while disabled or
        for the liveness root.  Returns the principal (or None when
        disabled)."""
        if not self.enabled or not self._users:
            # zero users + enabled would lock EVERYONE out including the
            # operator bootstrapping the first admin — enforcement
            # begins once an internal user exists
            return None
        if path == "/" and method in ("GET", "HEAD"):
            return None                   # ping stays open, like the
        return self.authenticate(authorization)        # reference's /
