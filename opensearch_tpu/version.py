"""Version constants, analog of libs/core Version (reference:
libs/core/src/main/java/org/opensearch/core/Version.java).

The wire/index format version is independent of the package version; it is
persisted in segment metadata and the translog header and checked on read.
"""

__version__ = "0.1.0"

# Bump when the on-disk segment layout changes incompatibly.
INDEX_FORMAT_VERSION = 1
# Bump when the translog record framing changes incompatibly.
TRANSLOG_FORMAT_VERSION = 1
# Wire protocol version for the node-to-node transport layer:
# major*100 + minor.  Handshakes negotiate min(local, remote) and refuse
# a major mismatch (TransportHandshaker analog).
TRANSPORT_PROTOCOL_VERSION = 101
