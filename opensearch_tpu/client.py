"""Python client mirroring the ``opensearch-py`` surface.

The reference ships language clients over its REST layer (ref
clients/..., and the separate opensearch-py project whose ``OpenSearch``
class + namespaced ``.indices/.cluster/.snapshot/...`` sub-clients are
the de-facto API).  This client speaks the same REST dialect against an
``opensearch_tpu`` node: method names, argument shapes, exception
classes (``NotFoundError``/``RequestError``/``ConflictError``/...) and
the ``helpers.bulk`` convenience match opensearch-py so user code ports
by changing the import.

Zero third-party deps: urllib transport with per-host failover.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Optional


class TransportError(Exception):
    """Base client error; mirrors opensearchpy.exceptions.TransportError
    (status_code, error, info).  ``headers`` carries the error
    response's HTTP headers and ``retry_after`` the parsed Retry-After
    hint (seconds, None when absent) — backpressure-aware callers like
    the open-loop load harness schedule 429 retries from it."""

    headers: dict = {}
    retry_after = None

    def __init__(self, status_code, error, info=None):
        super().__init__(status_code, error)
        self.status_code = status_code
        self.error = error
        self.info = info


class ConnectionError(TransportError):          # noqa: A001 — opensearch-py name
    pass


class RequestError(TransportError):             # 400
    pass


class AuthorizationException(TransportError):   # 403
    pass


class NotFoundError(TransportError):            # 404
    pass


class ConflictError(TransportError):            # 409
    pass


_HTTP_EXCEPTIONS = {400: RequestError, 403: AuthorizationException,
                    404: NotFoundError, 409: ConflictError}


class Transport:
    def __init__(self, hosts, timeout: float = 30.0, http_auth=None,
                 headers: Optional[dict] = None):
        import base64
        # default headers sent on every request (opaque id / traceparent
        # attribution, like opensearch-py's per-client headers)
        self.default_headers = dict(headers or {})
        self._auth_header = None
        if http_auth:
            if isinstance(http_auth, (tuple, list)):
                http_auth = ":".join(http_auth)
            self._auth_header = ("Basic " + base64.b64encode(
                http_auth.encode()).decode())
        self.hosts = []
        for h in hosts:
            if isinstance(h, str):
                self.hosts.append(h.rstrip("/"))
            else:
                self.hosts.append(
                    f"http://{h.get('host', 'localhost')}:"
                    f"{h.get('port', 9200)}")
        self.timeout = timeout

    def perform_request(self, method: str, path: str,
                        params: Optional[dict] = None, body=None,
                        headers: Optional[dict] = None):
        if params:
            from urllib.parse import urlencode
            qs = urlencode({k: (str(v).lower()
                                if isinstance(v, bool) else v)
                            for k, v in params.items() if v is not None})
            if qs:
                path = f"{path}?{qs}"
        hdrs = {**self.default_headers, **(headers or {})}
        if self._auth_header and "Authorization" not in hdrs:
            hdrs["Authorization"] = self._auth_header
        if isinstance(body, (dict, list)):
            data = json.dumps(body).encode()
            hdrs.setdefault("Content-Type", "application/json")
        elif isinstance(body, str):
            data = body.encode()
            hdrs.setdefault("Content-Type", "application/x-ndjson")
        else:
            data = body
        last_err = None
        for host in self.hosts:
            req = urllib.request.Request(host + path, data=data,
                                         method=method, headers=hdrs)
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout) as resp:
                    payload = resp.read()
                    ctype = resp.headers.get("Content-Type") or ""
                    if payload and "json" not in ctype:
                        # text surfaces (/_metrics Prometheus
                        # exposition, _cat tables) pass through verbatim
                        return payload.decode(errors="replace")
                    return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as e:
                payload = e.read()
                try:
                    info = json.loads(payload) if payload else {}
                except ValueError:
                    info = {"raw": payload.decode(errors="replace")}
                err = (info.get("error", {}) or {})
                reason = (err.get("reason") if isinstance(err, dict)
                          else str(err)) or str(e)
                cls = _HTTP_EXCEPTIONS.get(e.code, TransportError)
                exc = cls(e.code, reason, info)
                exc.headers = dict(e.headers.items())
                ra = e.headers.get("Retry-After")
                if ra is None and isinstance(err, dict):
                    # msearch-style sub-errors surface the hint in the
                    # body instead (the overall response is 200, so
                    # callers raising per-item errors land here too)
                    ra = err.get("retry_after_seconds")
                try:
                    exc.retry_after = float(ra) if ra is not None \
                        else None
                except (TypeError, ValueError):
                    exc.retry_after = None
                raise exc from None
            except (urllib.error.URLError, OSError) as e:
                last_err = e                   # try the next host
        raise ConnectionError("N/A", str(last_err), last_err)


class _Namespace:
    def __init__(self, transport: Transport):
        self.transport = transport


def _idx(index) -> str:
    return ",".join(index) if isinstance(index, (list, tuple)) else index


class IndicesClient(_Namespace):
    def create(self, index, body=None, params=None):
        return self.transport.perform_request(
            "PUT", f"/{index}", params, body or {})

    def delete(self, index, params=None):
        return self.transport.perform_request(
            "DELETE", f"/{_idx(index)}", params)

    def exists(self, index, params=None) -> bool:
        try:
            self.transport.perform_request("GET", f"/{_idx(index)}",
                                           params)
            return True
        except NotFoundError:
            return False

    def refresh(self, index=None, params=None):
        path = f"/{_idx(index)}/_refresh" if index else "/_refresh"
        return self.transport.perform_request("POST", path, params)

    def flush(self, index=None, params=None):
        path = f"/{_idx(index)}/_flush" if index else "/_flush"
        return self.transport.perform_request("POST", path, params)

    def forcemerge(self, index=None, params=None):
        path = (f"/{_idx(index)}/_forcemerge" if index
                else "/_forcemerge")
        return self.transport.perform_request("POST", path, params)

    def get(self, index, params=None):
        return self.transport.perform_request("GET", f"/{_idx(index)}",
                                              params)

    def get_mapping(self, index, params=None):
        return self.transport.perform_request(
            "GET", f"/{_idx(index)}/_mapping", params)

    def put_mapping(self, index, body, params=None):
        return self.transport.perform_request(
            "PUT", f"/{_idx(index)}/_mapping", params, body)

    def get_settings(self, index, params=None):
        return self.transport.perform_request(
            "GET", f"/{_idx(index)}/_settings", params)

    def put_settings(self, body, index, params=None):
        return self.transport.perform_request(
            "PUT", f"/{_idx(index)}/_settings", params, body)

    def analyze(self, index=None, body=None, params=None):
        path = f"/{index}/_analyze" if index else "/_analyze"
        return self.transport.perform_request("GET", path, params, body)

    def get_alias(self, index=None, name=None, params=None):
        path = "/_alias" if name is None else f"/_alias/{name}"
        if index:
            path = f"/{_idx(index)}{path}"
        return self.transport.perform_request("GET", path, params)

    def update_aliases(self, body, params=None):
        return self.transport.perform_request("POST", "/_aliases",
                                              params, body)

    def put_index_template(self, name, body, params=None):
        return self.transport.perform_request(
            "PUT", f"/_index_template/{name}", params, body)

    def delete_index_template(self, name, params=None):
        return self.transport.perform_request(
            "DELETE", f"/_index_template/{name}", params)


class ClusterClient(_Namespace):
    def health(self, params=None):
        return self.transport.perform_request("GET", "/_cluster/health",
                                              params)

    def state(self, params=None):
        return self.transport.perform_request("GET", "/_cluster/state",
                                              params)

    def get_settings(self, params=None):
        return self.transport.perform_request(
            "GET", "/_cluster/settings", params)

    def put_settings(self, body, params=None):
        return self.transport.perform_request(
            "PUT", "/_cluster/settings", params, body)


class CatClient(_Namespace):
    def indices(self, params=None):
        p = {"format": "json", **(params or {})}
        return self.transport.perform_request("GET", "/_cat/indices", p)

    def count(self, index=None, params=None):
        p = {"format": "json", **(params or {})}
        path = f"/_cat/count/{_idx(index)}" if index else "/_cat/count"
        return self.transport.perform_request("GET", path, p)

    def recovery(self, index=None, params=None):
        """Per-shard recovery state + the recovery.* metric family
        (corrupt-blob re-requests, retry accounting)."""
        p = {"format": "json", **(params or {})}
        path = (f"/_cat/recovery/{_idx(index)}" if index
                else "/_cat/recovery")
        return self.transport.perform_request("GET", path, p)

    def segments(self, params=None):
        """Per-segment rows with doc counts and HOST/DEVICE footprint
        columns (``size`` = host array bytes, ``size.device`` = bytes
        the residency ledger currently holds staged)."""
        p = {"format": "json", **(params or {})}
        return self.transport.perform_request("GET", "/_cat/segments", p)


class SnapshotClient(_Namespace):
    def create_repository(self, repository, body, params=None):
        return self.transport.perform_request(
            "PUT", f"/_snapshot/{repository}", params, body)

    def delete_repository(self, repository, params=None):
        return self.transport.perform_request(
            "DELETE", f"/_snapshot/{repository}", params)

    def create(self, repository, snapshot, body=None, params=None):
        return self.transport.perform_request(
            "PUT", f"/_snapshot/{repository}/{snapshot}", params,
            body or {})

    def get(self, repository, snapshot, params=None):
        return self.transport.perform_request(
            "GET", f"/_snapshot/{repository}/{snapshot}", params)

    def delete(self, repository, snapshot, params=None):
        return self.transport.perform_request(
            "DELETE", f"/_snapshot/{repository}/{snapshot}", params)

    def restore(self, repository, snapshot, body=None, params=None):
        return self.transport.perform_request(
            "POST", f"/_snapshot/{repository}/{snapshot}/_restore",
            params, body or {})


class IngestClient(_Namespace):
    def put_pipeline(self, id, body, params=None):       # noqa: A002
        return self.transport.perform_request(
            "PUT", f"/_ingest/pipeline/{id}", params, body)

    def get_pipeline(self, id=None, params=None):        # noqa: A002
        path = (f"/_ingest/pipeline/{id}" if id
                else "/_ingest/pipeline")
        return self.transport.perform_request("GET", path, params)

    def delete_pipeline(self, id, params=None):          # noqa: A002
        return self.transport.perform_request(
            "DELETE", f"/_ingest/pipeline/{id}", params)

    def simulate(self, body, id=None, params=None):      # noqa: A002
        path = (f"/_ingest/pipeline/{id}/_simulate" if id
                else "/_ingest/pipeline/_simulate")
        return self.transport.perform_request("POST", path, params, body)


class TasksClient(_Namespace):
    def list(self, params=None):                         # noqa: A003
        return self.transport.perform_request("GET", "/_tasks", params)

    def cancel(self, task_id, params=None):
        return self.transport.perform_request(
            "POST", f"/_tasks/{task_id}/_cancel", params)


class NodesClient(_Namespace):
    def stats(self, params=None):
        return self.transport.perform_request("GET", "/_nodes/stats",
                                              params)

    def trace(self, params=None):
        """Recent spans from the node's in-memory trace exporter
        (this engine's GET /_nodes/trace debug endpoint)."""
        return self.transport.perform_request("GET", "/_nodes/trace",
                                              params)

    def hot_threads(self, params=None):
        return self.transport.perform_request(
            "GET", "/_nodes/hot_threads", params)

    def flight_recorder(self, params=None):
        """Recent flight-recorder captures (slow-log trips, soak SLO
        breaches): GET /_nodes/flight_recorder."""
        return self.transport.perform_request(
            "GET", "/_nodes/flight_recorder", params)

    def device(self, params=None):
        """The ``device`` section of ``_nodes/stats`` per node: the
        residency ledger's per-index rollups, host↔device transfer
        counters (stage vs fetch-back), device-memory budget/eviction
        accounting, and the per-kernel XLA compile registry."""
        out = self.stats(params)
        return {nid: n.get("device", {})
                for nid, n in (out.get("nodes") or {}).items()}


class OpenSearch:
    """Drop-in analog of ``opensearchpy.OpenSearch`` for this node."""

    def __init__(self, hosts=None, timeout: float = 30.0, http_auth=None,
                 headers=None, **_ignored):
        hosts = hosts or [{"host": "localhost", "port": 9200}]
        if isinstance(hosts, (str, dict)):
            hosts = [hosts]
        self.transport = Transport(hosts, timeout=timeout,
                                   http_auth=http_auth, headers=headers)
        self.indices = IndicesClient(self.transport)
        self.cluster = ClusterClient(self.transport)
        self.cat = CatClient(self.transport)
        self.snapshot = SnapshotClient(self.transport)
        self.ingest = IngestClient(self.transport)
        self.tasks = TasksClient(self.transport)
        self.nodes = NodesClient(self.transport)

    def ping(self) -> bool:
        try:
            self.transport.perform_request("GET", "/")
            return True
        except TransportError:
            return False

    def info(self):
        return self.transport.perform_request("GET", "/")

    def metrics(self) -> str:
        """Prometheus text exposition (GET /_metrics) — returns the
        scrape body verbatim."""
        return self.transport.perform_request("GET", "/_metrics")

    def insights_top_queries(self, params=None):
        """Always-on top-N query attribution + per-plan-signature
        workload stats (GET /_insights/top_queries); ``by`` ranks by
        latency|cpu|heap, ``size`` bounds the list."""
        return self.transport.perform_request(
            "GET", "/_insights/top_queries", params)

    def index(self, index, body, id=None, params=None):  # noqa: A002
        if id is None:
            return self.transport.perform_request(
                "POST", f"/{index}/_doc", params, body)
        return self.transport.perform_request(
            "PUT", f"/{index}/_doc/{id}", params, body)

    def create(self, index, id, body, params=None):      # noqa: A002
        return self.transport.perform_request(
            "PUT", f"/{index}/_create/{id}", params, body)

    def get(self, index, id, params=None):               # noqa: A002
        return self.transport.perform_request(
            "GET", f"/{index}/_doc/{id}", params)

    def exists(self, index, id, params=None) -> bool:    # noqa: A002
        try:
            self.get(index, id, params)
            return True
        except NotFoundError:
            return False

    def delete(self, index, id, params=None):            # noqa: A002
        return self.transport.perform_request(
            "DELETE", f"/{index}/_doc/{id}", params)

    def update(self, index, id, body, params=None):      # noqa: A002
        return self.transport.perform_request(
            "POST", f"/{index}/_update/{id}", params, body)

    def search(self, index=None, body=None, params=None,
               allow_partial_search_results=None):
        path = (f"/{_idx(index)}/_search" if index else "/_search")
        if allow_partial_search_results is not None:
            params = dict(params or {})
            params["allow_partial_search_results"] = \
                allow_partial_search_results
        return self.transport.perform_request("POST", path, params,
                                              body or {})

    def msearch(self, body, index=None, params=None):
        path = (f"/{_idx(index)}/_msearch" if index else "/_msearch")
        if isinstance(body, list):
            body = "\n".join(json.dumps(x) for x in body) + "\n"
        return self.transport.perform_request("POST", path, params, body)

    def count(self, index=None, body=None, params=None):
        path = f"/{_idx(index)}/_count" if index else "/_count"
        return self.transport.perform_request("POST", path, params,
                                              body or {})

    def mget(self, body, index=None, params=None):
        path = f"/{index}/_mget" if index else "/_mget"
        return self.transport.perform_request("POST", path, params, body)

    def bulk(self, body, index=None, params=None):
        path = f"/{index}/_bulk" if index else "/_bulk"
        if isinstance(body, list):
            body = "\n".join(json.dumps(x) for x in body) + "\n"
        return self.transport.perform_request("POST", path, params, body)

    def scroll(self, scroll_id, params=None, body=None):
        b = dict(body or {})
        b["scroll_id"] = scroll_id
        return self.transport.perform_request("POST", "/_search/scroll",
                                              params, b)

    def clear_scroll(self, scroll_id, params=None):
        return self.transport.perform_request(
            "DELETE", "/_search/scroll", params,
            {"scroll_id": scroll_id})

    def create_pit(self, index, params=None):
        return self.transport.perform_request(
            "POST", f"/{_idx(index)}/_search/point_in_time",
            params or {"keep_alive": "1m"})

    def delete_pit(self, body, params=None):
        return self.transport.perform_request(
            "DELETE", "/_search/point_in_time", params, body)

    def delete_by_query(self, index, body, params=None):
        return self.transport.perform_request(
            "POST", f"/{_idx(index)}/_delete_by_query", params, body)

    def update_by_query(self, index, body=None, params=None):
        return self.transport.perform_request(
            "POST", f"/{_idx(index)}/_update_by_query", params,
            body or {})

    def reindex(self, body, params=None):
        return self.transport.perform_request("POST", "/_reindex",
                                              params, body)


class helpers:                                     # noqa: N801 — opensearch-py name
    """``opensearchpy.helpers`` analog (the bulk convenience)."""

    @staticmethod
    def bulk(client: OpenSearch, actions, chunk_size: int = 500,
             raise_on_error: bool = True):
        """Actions like opensearch-py: dicts with ``_index``/``_id``/
        ``_op_type`` meta keys + source fields.  Returns (ok_count,
        errors)."""
        ok, errors = 0, []
        batch = list(actions)
        for start in range(0, len(batch), chunk_size):
            lines = []
            for a in batch[start:start + chunk_size]:
                a = dict(a)
                op = a.pop("_op_type", "index")
                meta = {k: a.pop(k) for k in ("_index", "_id")
                        if k in a}
                src = a.pop("_source", a)
                lines.append(json.dumps({op: meta}))
                if op != "delete":
                    lines.append(json.dumps(src))
            resp = client.bulk("\n".join(lines) + "\n")
            for item in resp.get("items", []):
                res = next(iter(item.values()))
                if res.get("status", 200) < 300:
                    ok += 1
                else:
                    errors.append(item)
        if errors and raise_on_error:
            raise TransportError(
                None, f"{len(errors)} document(s) failed to index",
                errors)
        return ok, errors
