"""Bootstrap checks: refuse to start a production node on a broken host.

Analog of ``bootstrap/BootstrapChecks.java`` (ref server/src/main/java/
org/opensearch/bootstrap/BootstrapChecks.java:70): each check inspects
one host limit; in development mode failures are logged as warnings, in
production mode (the reference: publishing to a non-loopback address;
here: ``bootstrap.checks=true`` or binding a non-loopback host) any
failure aborts startup.  JVM-specific checks (heap size, G1GC, client
JVM) have no analog here; the accelerator-runtime check fills that slot.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from opensearch_tpu.common.errors import OpenSearchTpuError


class BootstrapCheckError(OpenSearchTpuError):
    status = 500


class BootstrapCheck:
    """One named predicate; returns an error message or None."""

    def __init__(self, name: str, fn: Callable[[], Optional[str]]):
        self.name = name
        self.fn = fn

    def run(self) -> Optional[str]:
        try:
            return self.fn()
        except Exception as e:  # noqa: BLE001 — a broken probe is a finding
            return f"check could not run: {e!r}"


def _file_descriptor_check(minimum: int = 4096) -> Optional[str]:
    """ref bootstrap/BootstrapChecks.java FileDescriptorCheck (65535 on
    Linux servers; relaxed here since shard files are columnar, not
    per-field)."""
    import resource

    soft, _hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft != resource.RLIM_INFINITY and soft < minimum:
        return (f"max file descriptors [{soft}] is too low, increase to "
                f"at least [{minimum}]")
    return None


def _max_map_count_check(minimum: int = 262144) -> Optional[str]:
    """ref MaxMapCountCheck — XLA/HBM staging mmaps many regions too."""
    path = "/proc/sys/vm/max_map_count"
    if not os.path.exists(path):        # non-Linux: not applicable
        return None
    with open(path) as f:
        count = int(f.read().strip())
    if count < minimum:
        return (f"max virtual memory areas vm.max_map_count [{count}] is "
                f"too low, increase to at least [{minimum}]")
    return None


def _max_threads_check(minimum: int = 1024) -> Optional[str]:
    """ref MaxNumberOfThreadsCheck (thread pools + per-search dispatch)."""
    import resource

    soft, _hard = resource.getrlimit(resource.RLIMIT_NPROC)
    if soft != resource.RLIM_INFINITY and soft < minimum:
        return (f"max number of threads [{soft}] is too low, increase "
                f"to at least [{minimum}]")
    return None


def _data_path_writable_check(data_path: str) -> Optional[str]:
    if not os.access(data_path, os.W_OK):
        return f"data path [{data_path}] is not writable"
    return None


def _accelerator_check() -> Optional[str]:
    """The heap/JVM slot: the compute backend must initialize.  Import
    only — device init is deferred to first use so a slow tunnel doesn't
    stall boot."""
    try:
        import jax  # noqa: F401
    except Exception as e:  # noqa: BLE001
        return f"jax runtime unavailable: {e!r}"
    return None


def default_checks(data_path: str) -> list[BootstrapCheck]:
    return [
        BootstrapCheck("file descriptors", _file_descriptor_check),
        BootstrapCheck("vm.max_map_count", _max_map_count_check),
        BootstrapCheck("max threads", _max_threads_check),
        BootstrapCheck("data path writable",
                       lambda: _data_path_writable_check(data_path)),
        BootstrapCheck("accelerator runtime", _accelerator_check),
    ]


def run_bootstrap_checks(checks: list[BootstrapCheck], *,
                         enforce: bool) -> list[str]:
    """Run all checks; returns failure messages.  ``enforce`` (production
    mode) raises BootstrapCheckError listing EVERY failure (the reference
    reports all failures at once, not just the first)."""
    import logging

    failures = []
    for c in checks:
        msg = c.run()
        if msg is not None:
            failures.append(f"[{c.name}] {msg}")
    if failures:
        if enforce:
            raise BootstrapCheckError(
                "node validation exception\nbootstrap checks failed\n"
                + "\n".join(failures))
        log = logging.getLogger("opensearch_tpu.bootstrap")
        for f in failures:
            log.warning("bootstrap check failure (dev mode): %s", f)
    return failures
