"""Node: service wiring + lifecycle + CLI entry point.

Analog of ``node/Node.java`` (ctor wiring at :400, start at :1249) and
``bootstrap/OpenSearch.main`` — at single-node scope: settings, indices
service, REST controller, HTTP transport.

Run: ``python -m opensearch_tpu.node --port 9200 --data-path ./data``
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import uuid

from opensearch_tpu.indices.service import IndicesService
from opensearch_tpu.rest.controller import RestController
from opensearch_tpu.rest.http_server import HttpServer


class Node:
    def __init__(self, data_path: str, name: str = "node-1",
                 cluster_name: str = "opensearch-tpu",
                 host: str = "127.0.0.1", port: int = 9200,
                 path_repo: "list[str] | None" = None):
        self.name = name
        self.host = host
        self.cluster_name = cluster_name
        self.node_id = uuid.uuid4().hex[:22]
        self.cluster_uuid = uuid.uuid4().hex[:22]
        self.data_path = data_path
        os.makedirs(data_path, exist_ok=True)
        self.indices = IndicesService(os.path.join(data_path, "indices"))
        from opensearch_tpu.snapshots.service import SnapshotsService
        from opensearch_tpu.search.contexts import ReaderContextRegistry
        from opensearch_tpu.search.pipeline import SearchPipelineService
        from opensearch_tpu.common.tasks import TaskManager
        from opensearch_tpu.common.fshealth import FsHealthService
        from opensearch_tpu.common.threadpool import ThreadPool
        self.thread_pool = ThreadPool()
        from opensearch_tpu.ingest.service import IngestService
        self.fs_health = FsHealthService(data_path)
        self.fs_health.check()
        self.ingest = IngestService(data_path)
        self.snapshots = SnapshotsService(self.indices, data_path,
                                          path_repo=path_repo)
        # remote-store mirroring resolves repositories late-bound
        self.indices.set_repo_resolver(self.snapshots._repo,
                                       self.snapshots.repo_mutex)
        self.contexts = ReaderContextRegistry()
        self.search_pipelines = SearchPipelineService(data_path)
        self.task_manager = TaskManager(name)
        from opensearch_tpu.search.backpressure import \
            SearchBackpressureService
        self.search_backpressure = SearchBackpressureService(
            self.task_manager, self.thread_pool)
        from opensearch_tpu.security.identity import IdentityService
        self.identity = IdentityService(data_path)
        # adaptive-selection stats surface (_nodes/stats, _cat/nodes);
        # populated by the cluster coordinator's scatter path — a
        # single-node deployment exposes an empty (but present) block
        from opensearch_tpu.cluster.response_collector import \
            ResponseCollectorService
        self.response_collector = ResponseCollectorService()
        # always-on top-N query attribution + per-plan-signature
        # workload stats (GET /_insights/top_queries, _nodes/stats
        # query_insights, /_metrics labeled series)
        from opensearch_tpu.search.insights import QueryInsightsService
        self.insights = QueryInsightsService(node_id=self.node_id)
        # per-tenant QoS + adaptive overload control (search/qos.py):
        # the AIMD controller connecting the admission ledger / flight
        # recorder / insights measurements to the shed-occupancy,
        # batcher-window, and tenant-share knobs
        from opensearch_tpu.search.qos import QosController
        self.qos = QosController(
            admission=self.search_backpressure.admission,
            insights=self.insights,
            backpressure=self.search_backpressure)
        self._init_cluster_settings()
        from opensearch_tpu.common.persistent_tasks import \
            PersistentTasksService
        self.persistent_tasks = PersistentTasksService(data_path)
        self.rest = RestController(self)
        self.persistent_tasks.register_executor(
            "indices:data/write/reindex", self.rest._do_reindex)
        self.http = HttpServer(self.rest, host=host, port=port)

    # actuator-ok (knob writes replay operator-set settings at boot)
    def _init_cluster_settings(self):
        """Dynamic cluster-settings registry + persistence
        (ClusterSettings / the _cluster/settings update API; consumers
        wire live like SearchService.java:360)."""
        import json as _json

        from opensearch_tpu.common.settings import (Setting, Settings,
                                                    SettingsRegistry)
        from opensearch_tpu.search import aggs as aggs_mod

        self._settings_file = os.path.join(self.data_path,
                                           "cluster_settings.json")
        stored = {}
        if os.path.exists(self._settings_file):
            with open(self._settings_file) as f:
                stored = _json.load(f)
        # transient settings live in memory only; persistent survive boot
        self.settings_buckets = {"persistent": dict(stored),
                                 "transient": {}}
        max_buckets = Setting.int_setting(
            "search.max_buckets", 65536, min_value=1, dynamic=True)
        auto_create = Setting.bool_setting(
            "action.auto_create_index", True, dynamic=True)
        max_scroll = Setting.int_setting(
            "search.max_open_scroll_context", 500, min_value=0,
            dynamic=True)
        cache_size = Setting.int_setting(
            "node.searchable_snapshot.cache.size", 256 << 20,
            min_value=0, dynamic=True)
        identity_enabled = Setting.bool_setting(
            "identity.enabled", False, dynamic=True)
        allow_partial = Setting.bool_setting(
            "search.default_allow_partial_search_results", True,
            dynamic=True)
        # compat-only: accepted and validated for client parity;
        # single-node allocation has no routing decisions to gate
        # knob-ok (tools/check_dead_settings.py)
        alloc_enable = Setting.str_setting(
            "cluster.routing.allocation.enable", "all", dynamic=True,
            choices=("all", "primaries", "new_primaries", "none"))
        from opensearch_tpu.common.errors import IllegalArgumentError

        def _bp_mode_check(v: str):
            if v not in ("monitor_only", "enforced", "disabled"):
                raise IllegalArgumentError(
                    f"Invalid SearchBackpressureMode: {v}")
        backpressure_mode = Setting(
            "search_backpressure.mode", "monitor_only", str,
            validator=_bp_mode_check, dynamic=True)
        bp_cpu = Setting.float_setting(
            "search_backpressure.node_duress.cpu_threshold", 0.9,
            min_value=0.0, dynamic=True)
        bp_heap = Setting.float_setting(
            "search_backpressure.node_duress.heap_threshold", 0.85,
            min_value=0.0, dynamic=True)
        bp_queue = Setting.int_setting(
            "search_backpressure.node_duress.search_queue_threshold",
            500, min_value=1, dynamic=True)
        bp_streak = Setting.int_setting(
            "search_backpressure.node_duress.num_successive_breaches",
            3, min_value=1, dynamic=True)
        bp_max_cc = Setting.int_setting(
            "search_backpressure.max_concurrent_searches", 256,
            min_value=1, dynamic=True)
        ars_enabled = Setting.bool_setting(
            "search.replica_selection.adaptive", True, dynamic=True)
        ars_shed = Setting.bool_setting(
            "search.replica_selection.shed_on_duress", True, dynamic=True)
        ars_spill = Setting.int_setting(
            "search.replica_selection.spill_outstanding", 8,
            min_value=0, dynamic=True)
        ars_shed_occ = Setting.float_setting(
            "search.replica_selection.shed_occupancy", 0.0,
            min_value=0.0, dynamic=True)
        # search-replica tier: checkpoint lag (ops behind the last
        # published checkpoint) past which a searcher is deranked by
        # the C3 selector like a duress node
        search_max_lag = Setting.int_setting(
            "search.replication.max_lag", 8, min_value=0, dynamic=True)
        max_keep_alive = Setting.time_setting(
            "search.max_keep_alive", 24 * 3600.0, dynamic=True)
        default_keep_alive = Setting.time_setting(
            "search.default_keep_alive", 300.0, dynamic=True)
        ins_enabled = Setting.bool_setting(
            "search.insights.enabled", True, dynamic=True)
        ins_top_n = Setting.int_setting(
            "search.insights.top_n", 10, min_value=1, dynamic=True)
        ins_window = Setting.time_setting(
            "search.insights.window", 300.0, dynamic=True)
        ins_coalesce = Setting.float_setting(
            "search.insights.coalesce_window_ms", 10.0,
            min_value=0.0, dynamic=True)
        # continuous batcher at the REST edge (search/engine.py):
        # window_ms 0 = auto-size from the measured insights coalesce
        # window (the PR-10 coalescability report's Δt)
        batcher_enabled = Setting.bool_setting(
            "search.batcher.enabled", True, dynamic=True)
        batcher_window = Setting.float_setting(
            "search.batcher.window_ms", 0.0, min_value=0.0,
            dynamic=True)
        batcher_max = Setting.int_setting(
            "search.batcher.max_batch", 64, min_value=2, dynamic=True)
        # per-tenant QoS (search/qos.py): weighted admission shares per
        # X-Opaque-Id ("tenantA:4,tenantB:1"; empty = one legacy pool),
        # the default pool's weight for unlabeled traffic, and the
        # adaptive AIMD controller's enable/pacing knobs
        from opensearch_tpu.search.qos import parse_tenant_shares

        def _shares_check(v: str):
            parse_tenant_shares(v)
        qos_shares = Setting(
            "search.qos.tenant_shares", "", str,
            validator=_shares_check, dynamic=True)
        qos_default_share = Setting.float_setting(
            "search.qos.default_share", 1.0, min_value=0.0,
            dynamic=True)
        qos_adaptive = Setting.bool_setting(
            "search.qos.adaptive", False, dynamic=True)
        qos_interval = Setting.float_setting(
            "search.qos.interval_s", 1.0, min_value=0.01, dynamic=True)
        # measured device-memory budget: 0 = unlimited; exceeding it
        # unstages least-recently-dispatched segments (ROADMAP item 5's
        # host↔device paging seed, common/device_ledger.py)
        device_budget = Setting.byte_size_setting(
            "device.memory.budget_bytes", 0, dynamic=True)
        # paged quantized index (index/codec.py + the device pager):
        # page accounting granularity, and the per-segment lowering
        # policy ("auto" quantizes segments >= QUANTIZED_MIN_DOCS)
        pager_page_bytes = Setting.byte_size_setting(
            "device.pager.page_bytes", 0, dynamic=True)
        quantized_mode = Setting.str_setting(
            "index.device.quantized", "auto", dynamic=True,
            choices=("auto", "on", "off"))
        # accelerator fault tolerance (common/device_health.py): the
        # per-kernel-class circuit breakers' trip threshold and the
        # open-state cooldown before a half-open probe is allowed
        dh_enabled = Setting.bool_setting(
            "device.health.enabled", True, dynamic=True)
        dh_threshold = Setting.int_setting(
            "device.health.failure_threshold", 3, min_value=1,
            dynamic=True)
        dh_interval = Setting.float_setting(
            "device.health.open_interval_s", 30.0, min_value=0.0,
            dynamic=True)
        from opensearch_tpu.indices.request_cache import (
            DEFAULT_MAX_BYTES, request_cache)
        req_cache_size = Setting.byte_size_setting(
            "indices.requests.cache.size", DEFAULT_MAX_BYTES,
            dynamic=True)
        # QoS-driven searcher elasticity (cluster/autoscaler.py): the
        # leader's control loop from admission/Retry-After evidence to
        # fleet mutation — enable gate, fleet bounds, the dwell window
        # hot/cold evidence must persist before an actuation, the
        # anti-flap cooldown between scale events, and the drain
        # deadline past which retirement escalates to hard-kill
        as_enabled = Setting.bool_setting(
            "cluster.autoscale.enabled", False, dynamic=True)
        as_min = Setting.int_setting(
            "cluster.autoscale.min_searchers", 1, min_value=0,
            dynamic=True)
        as_max = Setting.int_setting(
            "cluster.autoscale.max_searchers", 4, min_value=0,
            dynamic=True)
        as_dwell = Setting.float_setting(
            "cluster.autoscale.dwell_s", 3.0, min_value=0.0,
            dynamic=True)
        as_cooldown = Setting.float_setting(
            "cluster.autoscale.cooldown_s", 10.0, min_value=0.0,
            dynamic=True)
        as_drain_timeout = Setting.float_setting(
            "cluster.autoscale.drain_timeout_s", 5.0, min_value=0.0,
            dynamic=True)
        self.cluster_settings = SettingsRegistry(
            Settings(stored),
            [max_buckets, auto_create, max_scroll, cache_size,
             identity_enabled, alloc_enable, backpressure_mode,
             bp_cpu, bp_heap, bp_queue, bp_streak, bp_max_cc,
             ars_enabled, ars_shed, ars_spill, ars_shed_occ,
             search_max_lag,
             max_keep_alive, default_keep_alive, allow_partial,
             req_cache_size, ins_enabled, ins_top_n, ins_window,
             ins_coalesce, device_budget, pager_page_bytes,
             quantized_mode, dh_enabled, dh_threshold,
             dh_interval, batcher_enabled,
             batcher_window, batcher_max, qos_shares,
             qos_default_share, qos_adaptive, qos_interval,
             as_enabled, as_min, as_max, as_dwell, as_cooldown,
             as_drain_timeout])
        # per-tenant QoS knobs reach the live admission gate and the
        # controller immediately; persisted values replay at boot
        adm = self.search_backpressure.admission
        for setting, consumer in (
                (qos_shares,
                 lambda v: adm.set_tenant_shares(
                     parse_tenant_shares(v))),
                (qos_default_share, adm.set_default_share),
                (qos_adaptive, self.qos.set_enabled),
                (qos_interval, self.qos.set_interval_s)):
            self.cluster_settings.add_settings_update_consumer(
                setting, consumer)
            consumer(self.cluster_settings.get(setting))
        # continuous-batcher knobs land on engine module globals (the
        # DEFAULT_ALLOW_PARTIAL_RESULTS idiom); the insights coalesce
        # window doubles as the batcher's auto window so the Δt always
        # tracks the measured workload knob
        from opensearch_tpu.search import engine as engine_mod
        for setting, attr, conv in (
                (batcher_enabled, "BATCHER_ENABLED", bool),
                (batcher_window, "BATCHER_WINDOW_MS", float),
                (batcher_max, "BATCHER_MAX_BATCH", int),
                (ins_coalesce, "AUTO_WINDOW_MS", float)):
            def _apply_eng(v, attr=attr, conv=conv):
                setattr(engine_mod, attr, conv(v))
            self.cluster_settings.add_settings_update_consumer(
                setting, _apply_eng)
            _apply_eng(self.cluster_settings.get(setting))
        # autoscale knobs land on the autoscaler module globals: every
        # SearcherAutoscaler instance without a pinned override reads
        # them at tick time, so dynamic updates apply live
        from opensearch_tpu.cluster import autoscaler as asc_mod  # actuator-ok (operator-set knobs; the autoscaler audits its own decisions)
        for setting, attr, conv in (
                (as_enabled, "AUTOSCALE_ENABLED", bool),
                (as_min, "MIN_SEARCHERS", int),
                (as_max, "MAX_SEARCHERS", int),
                (as_dwell, "DWELL_S", float),
                (as_cooldown, "COOLDOWN_S", float),
                (as_drain_timeout, "DRAIN_TIMEOUT_S", float)):
            def _apply_asc(v, attr=attr, conv=conv):
                setattr(asc_mod, attr, conv(v))
            self.cluster_settings.add_settings_update_consumer(
                setting, _apply_asc)
            _apply_asc(self.cluster_settings.get(setting))
        # device-memory budget reaches the residency ledger immediately
        # (and persisted values replay at boot)
        from opensearch_tpu.common.device_ledger import (device_ledger,
                                                         device_pager)
        self.cluster_settings.add_settings_update_consumer(
            device_budget,
            lambda v: device_ledger().set_budget(int(v or 0)))
        device_ledger().set_budget(
            int(self.cluster_settings.get(device_budget) or 0))
        # pager page size reaches the process-global pager immediately;
        # the quantized-mode knob lands on the codec module global (the
        # DEFAULT_ALLOW_PARTIAL_RESULTS idiom) so the lowering decision
        # and the host parity fallback read one source of truth
        from opensearch_tpu.index import codec as codec_mod
        self.cluster_settings.add_settings_update_consumer(
            pager_page_bytes,
            lambda v: device_pager().set_page_bytes(int(v or 0)))
        device_pager().set_page_bytes(
            int(self.cluster_settings.get(pager_page_bytes) or 0))
        self.cluster_settings.add_settings_update_consumer(
            quantized_mode,
            lambda v: setattr(codec_mod, "QUANTIZED_MODE", str(v)))
        codec_mod.QUANTIZED_MODE = str(
            self.cluster_settings.get(quantized_mode))
        # device-health breaker knobs reach the process-global service
        # immediately (and persisted values replay at boot)
        from opensearch_tpu.common.device_health import device_health
        dh = device_health()
        for setting, consumer in (
                (dh_enabled, dh.set_enabled),
                (dh_threshold, dh.set_failure_threshold),
                (dh_interval, dh.set_open_interval_s)):
            self.cluster_settings.add_settings_update_consumer(
                setting, consumer)
            consumer(self.cluster_settings.get(setting))
        # query-insights knobs reach the live service immediately and
        # persisted values replay at boot
        ins = self.insights
        for setting, consumer in (
                (ins_enabled, ins.set_enabled),
                (ins_top_n, ins.set_top_n),
                (ins_window, ins.set_window_s),
                (ins_coalesce, ins.set_coalesce_window_ms)):
            self.cluster_settings.add_settings_update_consumer(
                setting, consumer)
            consumer(self.cluster_settings.get(setting))
        # search backpressure: the mode setting was validated-but-dead
        # before this PR — now every flip (and the node_duress knobs)
        # reaches the live service immediately, and persisted values
        # replay at boot (SearchBackpressureSettings' consumers)
        bp = self.search_backpressure
        for setting, consumer in (
                (backpressure_mode, bp.set_mode),
                (bp_cpu, bp.set_cpu_threshold),
                (bp_heap, bp.set_heap_threshold),
                (bp_queue, bp.set_queue_threshold),
                (bp_streak, bp.set_num_successive_breaches),
                (bp_max_cc, bp.set_max_concurrent_searches)):
            self.cluster_settings.add_settings_update_consumer(
                setting, consumer)
            consumer(self.cluster_settings.get(setting))
        # adaptive replica selection knobs land on module globals the
        # cluster coordinator reads per search (same idiom as
        # DEFAULT_ALLOW_PARTIAL_RESULTS below)
        from opensearch_tpu.cluster import response_collector as rc_mod
        self.cluster_settings.add_settings_update_consumer(
            ars_enabled,
            lambda v: setattr(rc_mod, "ADAPTIVE_ENABLED", bool(v)))
        self.cluster_settings.add_settings_update_consumer(
            ars_shed,
            lambda v: setattr(rc_mod, "SHED_ON_DURESS", bool(v)))
        self.cluster_settings.add_settings_update_consumer(
            ars_spill,
            lambda v: setattr(rc_mod, "SPILL_OUTSTANDING", int(v)))
        self.cluster_settings.add_settings_update_consumer(
            ars_shed_occ,
            lambda v: setattr(rc_mod, "SHED_OCCUPANCY", float(v)))
        self.cluster_settings.add_settings_update_consumer(
            search_max_lag,
            lambda v: setattr(rc_mod, "SEARCH_MAX_LAG", int(v)))
        rc_mod.SEARCH_MAX_LAG = int(
            self.cluster_settings.get(search_max_lag))
        rc_mod.ADAPTIVE_ENABLED = bool(
            self.cluster_settings.get(ars_enabled))
        rc_mod.SHED_ON_DURESS = bool(self.cluster_settings.get(ars_shed))
        rc_mod.SPILL_OUTSTANDING = int(
            self.cluster_settings.get(ars_spill))
        rc_mod.SHED_OCCUPANCY = float(
            self.cluster_settings.get(ars_shed_occ))
        self.cluster_settings.add_settings_update_consumer(
            req_cache_size,
            lambda v: request_cache().set_max_bytes(int(v)))
        request_cache().set_max_bytes(
            int(self.cluster_settings.get(req_cache_size)))
        from opensearch_tpu.search import executor as executor_mod
        self.cluster_settings.add_settings_update_consumer(
            allow_partial,
            lambda v: setattr(executor_mod,
                              "DEFAULT_ALLOW_PARTIAL_RESULTS", bool(v)))
        executor_mod.DEFAULT_ALLOW_PARTIAL_RESULTS = bool(
            self.cluster_settings.get(allow_partial))
        self.cluster_settings.add_settings_update_consumer(
            max_keep_alive,
            lambda v: setattr(self.contexts, "max_keep_alive_s", v))
        # search.default_keep_alive was registered-but-dead before this
        # PR (tools/check_dead_settings.py caught it): it now sets the
        # keepalive a PIT opened without an explicit keep_alive gets
        self.cluster_settings.add_settings_update_consumer(
            default_keep_alive,
            lambda v: setattr(self.contexts, "default_keep_alive_s",
                              float(v)))
        self.contexts.default_keep_alive_s = float(
            self.cluster_settings.get(default_keep_alive))
        # cluster-level slowlog threshold DEFAULTS (per-index settings
        # override; the reference layers index settings over node ones)
        from opensearch_tpu.indices import service as indices_mod
        for prefix in ("search.slowlog.threshold.query",
                       "indexing.slowlog.threshold.index"):
            for level in ("warn", "info", "debug", "trace"):
                key = f"{prefix}.{level}"
                s = Setting(key, None, lambda x: x, dynamic=True)
                self.cluster_settings.register(s)

                def _apply(v, key=key):
                    if v is None:
                        indices_mod.SLOWLOG_DEFAULTS.pop(key, None)
                    else:
                        indices_mod.SLOWLOG_DEFAULTS[key] = v
                self.cluster_settings.add_settings_update_consumer(
                    s, _apply)
                _apply(self.cluster_settings.get(s))   # replay persisted
        # remote clusters configure via affix keys (RemoteClusterService)
        self.cluster_settings.register_prefix("cluster.remote")
        from opensearch_tpu.transport.remote import RemoteClusterService
        self.remotes = RemoteClusterService(
            lambda: self.cluster_settings.settings.as_dict())
        self.cluster_settings.add_settings_update_consumer(
            max_buckets, lambda v: setattr(aggs_mod, "MAX_BUCKETS", v))
        self.cluster_settings.add_settings_update_consumer(
            auto_create, lambda v: setattr(self.indices, "auto_create", v))
        self.cluster_settings.add_settings_update_consumer(
            max_scroll, lambda v: setattr(self.contexts, "_max_open", v))
        self.cluster_settings.add_settings_update_consumer(
            cache_size, lambda v: self.indices.file_cache.set_max_bytes(v))
        self.cluster_settings.add_settings_update_consumer(
            identity_enabled,
            lambda v: setattr(self.identity, "enabled", v))
        # replay persisted values into the consumers at boot
        aggs_mod.MAX_BUCKETS = self.cluster_settings.get(max_buckets)
        self.indices.auto_create = self.cluster_settings.get(auto_create)
        self.contexts._max_open = self.cluster_settings.get(max_scroll)
        self.indices.file_cache.set_max_bytes(
            self.cluster_settings.get(cache_size))
        self.identity.enabled = self.cluster_settings.get(
            identity_enabled)

    def update_cluster_settings(self, persistent: dict | None = None,
                                transient: dict | None = None) -> dict:
        """Two-bucket cluster settings (ClusterUpdateSettingsRequest):
        null values reset; transient overrides persistent; only the
        persistent bucket survives restart."""
        import json as _json

        touched = set(persistent or {}) | set(transient or {})
        # validate BEFORE mutating the buckets (a rejected update must
        # leave them unchanged)
        self.cluster_settings.validate(
            {k: v for k, v in {**(persistent or {}),
                               **(transient or {})}.items()
             if v is not None})
        for bucket, ups in (("persistent", persistent),
                            ("transient", transient)):
            d = self.settings_buckets[bucket]
            for k, v in (ups or {}).items():
                if v is None:
                    d.pop(k, None)
                else:
                    d[k] = v
        # the EFFECTIVE value of a touched key is transient over
        # persistent over default — never just this request's value
        # (ClusterSettings precedence)
        effective = {**self.settings_buckets["persistent"],
                     **self.settings_buckets["transient"]}
        self.cluster_settings.apply_update(
            {k: effective.get(k) for k in touched})
        tmp = self._settings_file + ".tmp"
        with open(tmp, "w") as f:
            _json.dump(self.settings_buckets["persistent"], f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._settings_file)
        return {"acknowledged": True,
                "persistent": dict(self.settings_buckets["persistent"]),
                "transient": dict(self.settings_buckets["transient"])}

    @property
    def port(self) -> int:
        return self.http.port

    def start(self):
        from opensearch_tpu.bootstrap import (default_checks,
                                              run_bootstrap_checks)
        # the reference enforces once the node publishes beyond
        # loopback (BootstrapChecks.enforceLimits); dev mode only warns
        enforce = (self.host not in ("127.0.0.1", "localhost", "::1")
                   or os.environ.get("OSTPU_ENFORCE_BOOTSTRAP") == "1")
        run_bootstrap_checks(default_checks(self.data_path),
                             enforce=enforce)
        if self.identity.enabled and self.host not in ("127.0.0.1",
                                                       "localhost", "::1"):
            import logging
            logging.getLogger("opensearch_tpu.security").warning(
                "identity.enabled is set with a non-loopback bind [%s] "
                "and no TLS: basic-auth credentials travel in cleartext "
                "(the reference's security plugin requires TLS here)",
                self.host)
        self.http.start()
        # overload monitor: evaluates node duress on a cadence even when
        # no new searches arrive to tick it (SearchBackpressureService's
        # scheduled run)
        self.search_backpressure.start_monitor()
        # periodic disk probe (FsHealthService.monitorFSHealth's schedule):
        # health was previously only refreshed when _nodes/stats was read —
        # a dead disk between reads went unnoticed
        self.fs_health.start_probe(
            float(os.environ.get("OSTPU_FSHEALTH_INTERVAL", "5.0")),
            name=f"fshealth-{self.name}")
        # re-run persistent tasks that never completed (crash between
        # submit and completion); executors are idempotent
        self.persistent_tasks.resume_incomplete()
        return self

    def stop(self):
        # idempotent (and safe when start() never ran): double-stop in a
        # test teardown must not re-close engines or hang on the HTTP
        # server's shutdown handshake
        if getattr(self, "_stopped", False):
            return
        self._stopped = True
        self.search_backpressure.stop_monitor()
        self.fs_health.stop_probe()
        self.http.stop()
        self.indices.close()
        # bounded-join the (process-global) query-engine workers; safe
        # when never started, idempotent on double-stop
        from opensearch_tpu.search.engine import query_engine
        query_engine().shutdown()
        self.thread_pool.shutdown()


def main(argv=None):
    ap = argparse.ArgumentParser(prog="opensearch-tpu")
    ap.add_argument("--port", type=int, default=9200)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--data-path", default="./data")
    ap.add_argument("--name", default="node-1")
    ap.add_argument("--cluster-name", default="opensearch-tpu")
    args = ap.parse_args(argv)

    node = Node(args.data_path, name=args.name,
                cluster_name=args.cluster_name, host=args.host,
                port=args.port).start()
    print(f"[{args.name}] listening on http://{args.host}:{node.port} "
          f"(data: {args.data_path})", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        stop.wait()
    finally:
        node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
