"""Node: service wiring + lifecycle + CLI entry point.

Analog of ``node/Node.java`` (ctor wiring at :400, start at :1249) and
``bootstrap/OpenSearch.main`` — at single-node scope: settings, indices
service, REST controller, HTTP transport.

Run: ``python -m opensearch_tpu.node --port 9200 --data-path ./data``
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import uuid

from opensearch_tpu.indices.service import IndicesService
from opensearch_tpu.rest.controller import RestController
from opensearch_tpu.rest.http_server import HttpServer


class Node:
    def __init__(self, data_path: str, name: str = "node-1",
                 cluster_name: str = "opensearch-tpu",
                 host: str = "127.0.0.1", port: int = 9200):
        self.name = name
        self.cluster_name = cluster_name
        self.node_id = uuid.uuid4().hex[:22]
        self.cluster_uuid = uuid.uuid4().hex[:22]
        self.data_path = data_path
        os.makedirs(data_path, exist_ok=True)
        self.indices = IndicesService(os.path.join(data_path, "indices"))
        from opensearch_tpu.snapshots.service import SnapshotsService
        from opensearch_tpu.search.contexts import ReaderContextRegistry
        from opensearch_tpu.search.pipeline import SearchPipelineService
        from opensearch_tpu.common.tasks import TaskManager
        self.snapshots = SnapshotsService(self.indices, data_path)
        self.contexts = ReaderContextRegistry()
        self.search_pipelines = SearchPipelineService(data_path)
        self.task_manager = TaskManager(name)
        self.rest = RestController(self)
        self.http = HttpServer(self.rest, host=host, port=port)

    @property
    def port(self) -> int:
        return self.http.port

    def start(self):
        self.http.start()
        return self

    def stop(self):
        self.http.stop()
        self.indices.close()


def main(argv=None):
    ap = argparse.ArgumentParser(prog="opensearch-tpu")
    ap.add_argument("--port", type=int, default=9200)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--data-path", default="./data")
    ap.add_argument("--name", default="node-1")
    ap.add_argument("--cluster-name", default="opensearch-tpu")
    args = ap.parse_args(argv)

    node = Node(args.data_path, name=args.name,
                cluster_name=args.cluster_name, host=args.host,
                port=args.port).start()
    print(f"[{args.name}] listening on http://{args.host}:{node.port} "
          f"(data: {args.data_path})", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        stop.wait()
    finally:
        node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
