"""Span / interval matching as batched device programs.

Lucene's span family (``SpanNearQuery``, ``SpanFirstQuery``) and the
intervals query walk position iterators doc-at-a-time (ref lucene
``NearSpansOrdered``; ref server/src/main/java/org/opensearch/index/
query/SpanNearQueryBuilder.java:51, IntervalQueryBuilder.java:43).  The
TPU formulation extends the phrase kernel's (doc, position) key sets:

- ordered near: anchor every occurrence of clause 0; for each later
  clause greedily take its SMALLEST position after the previous clause's
  match (binary search on the sorted key array).  Greedy-minimal is
  optimal (exchange argument), so an anchor matches iff the greedy chain
  ends within ``last - first - (k-1) <= slop``.
- unordered near (2 clauses): nearest occurrence of the other term on
  either side of the anchor, ``|gap| <= slop``.
- first: anchor position ``< end``.

Match frequency per doc is a scatter-add of surviving anchors, scored
BM25-style like the phrase kernel.
"""

from __future__ import annotations

import opensearch_tpu.common.jaxenv  # noqa: F401

import jax.numpy as jnp

from opensearch_tpu.ops.phrase import (KEY_PAD, POS_BASE,
                                       gather_term_positions)


def span_near_freqs(postings, term_ids, term_active, *,
                    budgets: tuple[int, ...], n_pad: int, ordered: bool,
                    slop, end):
    """Per-doc count of clause-0 occurrences that start a span match.

    ``slop`` (traced scalar): max total gap between consecutive clauses.
    ``end`` (traced scalar): spans must start before this analyzer
    position (span_first); pass a huge value to disable.
    """
    docs0, pos0, ok = gather_term_positions(
        postings["offsets"], postings["pos_offsets"],
        postings["positions"], postings["doc_ids"], term_ids[0],
        term_active[0], budget=budgets[0], pad_doc=n_pad - 1)
    ok = ok & (pos0 < end)
    prev = pos0
    for j in range(1, len(budgets)):
        docs_j, pos_j, valid_j = gather_term_positions(
            postings["offsets"], postings["pos_offsets"],
            postings["positions"], postings["doc_ids"], term_ids[j],
            term_active[j], budget=budgets[j], pad_doc=n_pad - 1)
        keys_j = jnp.where(valid_j,
                           docs_j.astype(jnp.int64) * POS_BASE + pos_j,
                           KEY_PAD)
        anchor_key = docs0.astype(jnp.int64) * POS_BASE + prev
        if ordered:
            # smallest occurrence strictly after the previous match; a
            # searchsorted past the end must NOT be clamp-accepted (a
            # clause whose position count exactly fills its bucket has
            # no KEY_PAD slot, and the clamped last key sits BEFORE the
            # anchor — an out-of-order false match)
            raw = jnp.searchsorted(keys_j, anchor_key, side="right")
            loc = jnp.clip(raw, 0, budgets[j] - 1)
            key = keys_j[loc]
            same_doc = (key // POS_BASE) == docs0
            ok = (ok & (raw < budgets[j]) & same_doc
                  & (key != KEY_PAD) & (key > anchor_key))
            prev = jnp.where(same_doc, (key % POS_BASE).astype(prev.dtype),
                             prev)
        else:
            # nearest occurrence on either side of the anchor; when both
            # clauses are the SAME term the anchor's own occurrence is
            # in keys_j and must not satisfy itself (Lucene requires two
            # distinct spans), so scan loc-1..loc+1 excluding self
            self_key = jnp.where(term_ids[j] == term_ids[0],
                                 anchor_key, jnp.int64(-1))
            loc = jnp.searchsorted(keys_j, anchor_key)

            def gap(idx):
                oob = (idx < 0) | (idx >= budgets[j])
                key = keys_j[jnp.clip(idx, 0, budgets[j] - 1)]
                same = (key // POS_BASE) == docs0
                g = jnp.abs((key % POS_BASE) - pos0) - 1
                return jnp.where(same & (key != KEY_PAD) & ~oob
                                 & (key != self_key), g, POS_BASE)
            best = jnp.minimum(jnp.minimum(gap(loc - 1), gap(loc)),
                               gap(loc + 1))
            ok = ok & (best <= slop)
    if ordered and len(budgets) > 1:
        ok = ok & (prev - pos0 - (len(budgets) - 1) <= slop)
    return jnp.zeros(n_pad, jnp.float32).at[docs0].add(
        ok.astype(jnp.float32))
