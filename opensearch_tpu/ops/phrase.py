"""Exact-phrase matching as a batched device program.

Lucene's ``PhraseQuery`` walks postings doc-at-a-time advancing position
iterators in lockstep (``ExactPhraseMatcher``).  The TPU formulation is
set-membership over (doc, position) keys:

- every occurrence of phrase term j is encoded as ``doc * POS_BASE +
  position`` — the key arrays are sorted by construction (postings are
  doc-ascending, positions ascending within a doc);
- an occurrence of the anchor term (position offset 0) starts a phrase iff
  for every other term j the key ``doc * POS_BASE + pos + off_j`` exists in
  term j's key set (binary search via ``searchsorted``);
- phrase frequency per doc is a scatter-add of surviving anchors, then BM25
  scores it with idf = sum of the terms' idfs (Lucene PhraseWeight).
"""

from __future__ import annotations

import opensearch_tpu.common.jaxenv  # noqa: F401

import jax.numpy as jnp

POS_BASE = 1 << 22  # > any token position (position_increment_gap padded)
KEY_PAD = jnp.iinfo(jnp.int64).max


def gather_term_positions(offsets, pos_offsets, positions, doc_ids, t_id,
                          active, *, budget: int, pad_doc: int):
    """All (doc, position) occurrences of one term, as fixed-size arrays.

    Returns (docs[B], pos[B], valid[B]).  ``budget`` must cover the term's
    total position count in this segment (host-known, bucketed pow2).
    """
    e0 = offsets[t_id]
    e1 = jnp.where(active, offsets[t_id + 1], e0)
    p0 = pos_offsets[e0]
    p1 = pos_offsets[e1]
    i = jnp.arange(budget, dtype=jnp.int32)
    valid = i < (p1 - p0)
    pidx = jnp.where(valid, p0 + i, 0)
    pos = positions[pidx]
    # owning posting entry: pos_offsets[e] <= pidx < pos_offsets[e+1]
    entry = jnp.searchsorted(pos_offsets, pidx, side="right").astype(jnp.int32) - 1
    entry = jnp.clip(entry, 0, doc_ids.shape[0] - 1)
    docs = jnp.where(valid, doc_ids[entry], pad_doc)
    return docs, pos, valid


def phrase_freqs(postings, term_ids, term_active, offsets_in_phrase, *,
                 budgets: tuple[int, ...], n_pad: int):
    """Per-doc exact-phrase frequency.

    ``postings`` is the staged dict (offsets/pos_offsets/positions/doc_ids);
    ``term_ids[j]`` / ``offsets_in_phrase[j]`` describe phrase slot j
    (analyzer positions, so stopword gaps are honored); ``budgets[j]`` is the
    static gather budget for slot j.  Slot 0 is the anchor.
    """
    docs0, pos0, ok = gather_term_positions(
        postings["offsets"], postings["pos_offsets"], postings["positions"],
        postings["doc_ids"], term_ids[0], term_active[0],
        budget=budgets[0], pad_doc=n_pad - 1)
    base0 = offsets_in_phrase[0]
    for j in range(1, len(budgets)):
        docs_j, pos_j, valid_j = gather_term_positions(
            postings["offsets"], postings["pos_offsets"], postings["positions"],
            postings["doc_ids"], term_ids[j], term_active[j],
            budget=budgets[j], pad_doc=n_pad - 1)
        keys_j = jnp.where(valid_j,
                           docs_j.astype(jnp.int64) * POS_BASE + pos_j,
                           KEY_PAD)
        target = (docs0.astype(jnp.int64) * POS_BASE + pos0
                  + (offsets_in_phrase[j] - base0))
        loc = jnp.searchsorted(keys_j, target)
        loc = jnp.clip(loc, 0, budgets[j] - 1)
        ok = ok & (keys_j[loc] == target)
    return jnp.zeros(n_pad, jnp.float32).at[docs0].add(ok.astype(jnp.float32))
