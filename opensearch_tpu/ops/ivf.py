"""IVF (inverted-file) approximate k-NN: k-means coarse quantizer trained
on device + cluster-probed exact scoring, optionally over PQ codes.

The reference ecosystem's ANN engines (FAISS IVF/IVFPQ via the
opensearch-knn plugin JNI, SPI at server/src/main/java/org/opensearch/
plugins/SearchPlugin.java:151) are C++ with hand-written SIMD; graph-based
HNSW is TPU-hostile (pointer chasing).  The TPU-native formulation keeps
everything as dense matmul + gather:

- training: Lloyd's iterations are one [n, d] x [d, c] matmul (MXU) for
  assignment + one scatter-add for the centroid update, all jitted;
- storage: vectors are re-laid-out as [nlist, c_pad, d] — cluster-major,
  padded to the max cluster size — so a probe is a static-shape gather,
  not a variable-length postings walk;
- search: query -> top-nprobe centroids ([nlist] matmul + top_k) ->
  gather [nprobe, c_pad, d] -> scored like the exact kernel -> top_k.
  Static nprobe/c_pad keep the whole program XLA-compilable;
- IVF-PQ: per-subspace codebooks ([m, 256, dsub]) turn each probe into a
  LUT build (one small matmul) + table gather, trading recall for an
  8-32x smaller resident set (BASELINE config #3's IVF-PQ class).

Score translations match ops/knn.py (the opensearch-knn space contract),
so ANN hits are drop-in comparable with exact ones.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np

import opensearch_tpu.common.jaxenv  # noqa: F401
import jax
import jax.numpy as jnp
from jax import lax

from opensearch_tpu.index.segment import pad_pow2


@partial(jax.jit, static_argnames=("n_clusters",))
def _kmeans_step(vectors, valid, centroids, *, n_clusters: int):
    """One Lloyd iteration: assign (matmul + argmin) and update
    (scatter-add mean).  Empty clusters keep their previous centroid."""
    v2 = jnp.sum(vectors * vectors, axis=1, keepdims=True)      # [n, 1]
    c2 = jnp.sum(centroids * centroids, axis=1)[None, :]        # [1, c]
    d2 = v2 - 2.0 * (vectors @ centroids.T) + c2                # [n, c] MXU
    assign = jnp.argmin(jnp.where(valid[:, None], d2, jnp.inf), axis=1)
    assign = jnp.where(valid, assign, n_clusters)               # dead slot
    sums = jax.ops.segment_sum(
        jnp.where(valid[:, None], vectors, 0.0), assign,
        num_segments=n_clusters + 1)[:n_clusters]
    counts = jax.ops.segment_sum(
        valid.astype(jnp.float32), assign,
        num_segments=n_clusters + 1)[:n_clusters]
    new = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None],
                    centroids)
    return new, assign


def train_kmeans(vectors: np.ndarray, valid: np.ndarray, n_clusters: int,
                 iters: int = 10, seed: int = 17):
    """k-means on device; returns (centroids [c, d] f32, assign [n] i32).
    Init = random valid points (k-means++ would add host loops for little
    gain at these cluster counts)."""
    rng = np.random.default_rng(seed)
    valid_idx = np.flatnonzero(valid)
    if len(valid_idx) == 0:
        raise ValueError("no valid vectors to train on")
    pick = rng.choice(valid_idx, size=n_clusters,
                      replace=len(valid_idx) < n_clusters)
    centroids = jnp.asarray(vectors[pick], jnp.float32)  # staging-ok: adopted by DeviceSegment.ann_staged
    v = jnp.asarray(vectors, jnp.float32)  # staging-ok: adopted by DeviceSegment.ann_staged
    m = jnp.asarray(valid, bool)  # staging-ok: adopted by DeviceSegment.ann_staged
    assign = None
    for _ in range(iters):
        centroids, assign = _kmeans_step(v, m, centroids,
                                         n_clusters=n_clusters)
    return np.asarray(centroids), np.asarray(assign)


@dataclass
class IvfIndex:
    """Cluster-major vector layout for static-shape probing."""

    centroids: np.ndarray        # [nlist, d] f32
    grouped: np.ndarray          # [nlist, c_pad, d] f32
    grouped_ids: np.ndarray      # [nlist, c_pad] i32 (doc local ids; -1 pad)
    grouped_valid: np.ndarray    # [nlist, c_pad] bool
    nlist: int
    c_pad: int

    @staticmethod
    def build(vectors: np.ndarray, valid: np.ndarray, nlist: int,
              iters: int = 10, seed: int = 17) -> "IvfIndex":
        n, d = vectors.shape
        nlist = max(1, min(nlist, int(valid.sum())))
        centroids, assign = train_kmeans(vectors, valid, nlist, iters, seed)
        order = np.argsort(assign[valid], kind="stable")
        ids = np.flatnonzero(valid)[order]
        clusters = assign[ids]
        counts = np.bincount(clusters, minlength=nlist)
        c_pad = pad_pow2(max(int(counts.max()), 1))
        grouped = np.zeros((nlist, c_pad, d), np.float32)
        grouped_ids = np.full((nlist, c_pad), -1, np.int32)
        grouped_valid = np.zeros((nlist, c_pad), bool)
        starts = np.zeros(nlist + 1, np.int64)
        starts[1:] = np.cumsum(counts)
        for c in range(nlist):
            rows = ids[starts[c]: starts[c + 1]]
            grouped[c, : len(rows)] = vectors[rows]
            grouped_ids[c, : len(rows)] = rows
            grouped_valid[c, : len(rows)] = True
        return IvfIndex(centroids=centroids, grouped=grouped,
                        grouped_ids=grouped_ids,
                        grouped_valid=grouped_valid,
                        nlist=nlist, c_pad=c_pad)

    def device(self):
        return (jnp.asarray(self.centroids), jnp.asarray(self.grouped),  # staging-ok: adopted by DeviceSegment.ann_staged
                jnp.asarray(self.grouped_ids),  # staging-ok: adopted by DeviceSegment.ann_staged
                jnp.asarray(self.grouped_valid))  # staging-ok: adopted by DeviceSegment.ann_staged


def _space_scores(dots, v2, q, space: str):
    """Shared opensearch-knn score translation given dot products and
    per-vector squared norms."""
    if space == "l2":
        d2 = jnp.maximum(v2 - 2.0 * dots + jnp.dot(q, q), 0.0)
        return 1.0 / (1.0 + d2)
    if space == "cosinesimil":
        qn = jnp.sqrt(jnp.dot(q, q))
        cos = dots / jnp.maximum(jnp.sqrt(v2) * qn, 1e-30)
        return (1.0 + cos) / 2.0
    if space == "innerproduct":
        return jnp.where(dots >= 0, dots + 1.0, 1.0 / (1.0 - dots))
    raise ValueError(f"unknown space [{space}]")


@partial(jax.jit, static_argnames=("space", "k", "nprobe"))
def ivf_search(centroids, grouped, grouped_ids, grouped_valid, query,
               live, *, space: str, k: int, nprobe: int):
    """Single query -> (scores [k], local doc ids [k]; -1/-inf padding).

    ``live`` is the segment's [n_docs_pad] live mask, applied post-gather
    so deletes need no IVF rebuild (the filter-during-search the FAISS
    integration does with pre-filter bitsets).
    """
    q = query.astype(jnp.float32)
    # coarse: nearest nprobe centroids by l2 (standard IVF contract)
    c2 = jnp.sum(centroids * centroids, axis=1)
    cd = c2 - 2.0 * (centroids @ q)                       # + q2 const
    _, probes = lax.top_k(-cd, nprobe)                    # [nprobe]
    pv = grouped[probes]                                  # [P, c_pad, d]
    pids = grouped_ids[probes]                            # [P, c_pad]
    pvalid = grouped_valid[probes]
    flat_v = pv.reshape(-1, pv.shape[-1])                 # [P*c_pad, d]
    flat_ids = pids.reshape(-1)
    dots = flat_v @ q
    v2 = jnp.sum(flat_v * flat_v, axis=1)
    scores = _space_scores(dots, v2, q, space)
    ok = (pvalid.reshape(-1)
          & live[jnp.clip(flat_ids, 0, live.shape[0] - 1)]
          & (flat_ids >= 0))
    scores = jnp.where(ok, scores, -jnp.inf)
    vals, idx = lax.top_k(scores, k)
    return vals, jnp.where(vals > -jnp.inf, flat_ids[idx], -1)


@partial(jax.jit, static_argnames=("space", "k", "nprobe"))
def ivf_search_batch(centroids, grouped, grouped_ids, grouped_valid,
                     queries, live, *, space: str, k: int, nprobe: int):
    """Batched queries [Q, d] -> (scores [Q, k], ids [Q, k])."""
    fn = partial(ivf_search, space=space, k=k, nprobe=nprobe)
    return jax.vmap(
        lambda q: fn(centroids, grouped, grouped_ids, grouped_valid, q,
                     live))(queries)


# ---------------------------------------------------------------------------
# IVF-PQ: product-quantized residual codes inside each cluster.
# ---------------------------------------------------------------------------


@dataclass
class IvfPqIndex:
    """IVF coarse quantizer + PQ codes of the residuals (vector -
    centroid), FAISS IVFPQ layout re-expressed as dense arrays."""

    centroids: np.ndarray        # [nlist, d]
    codebooks: np.ndarray        # [m, 256, dsub]
    grouped_codes: np.ndarray    # [nlist, c_pad, m] uint8
    grouped_ids: np.ndarray      # [nlist, c_pad] i32
    grouped_valid: np.ndarray    # [nlist, c_pad] bool
    nlist: int
    c_pad: int
    m: int
    dsub: int

    @staticmethod
    def build(vectors: np.ndarray, valid: np.ndarray, nlist: int,
              m: int = 8, iters: int = 10, pq_iters: int = 8,
              seed: int = 17) -> "IvfPqIndex":
        n, d = vectors.shape
        if d % m != 0:
            raise ValueError(f"dim [{d}] not divisible by m [{m}]")
        dsub = d // m
        flat = IvfIndex.build(vectors, valid, nlist, iters, seed)
        nlist, c_pad = flat.nlist, flat.c_pad
        # residuals of every stored vector against its cluster centroid
        res = flat.grouped - flat.centroids[:, None, :]   # [nlist,c_pad,d]
        res_flat = res.reshape(-1, d)
        vmask = flat.grouped_valid.reshape(-1)
        codebooks = np.zeros((m, 256, dsub), np.float32)
        codes = np.zeros((nlist * c_pad, m), np.uint8)
        for sub in range(m):
            block = res_flat[:, sub * dsub: (sub + 1) * dsub]
            cb, assign = train_kmeans(block, vmask,
                                      min(256, max(1, int(vmask.sum()))),
                                      pq_iters, seed + sub)
            codebooks[sub, : cb.shape[0]] = cb
            codes[:, sub] = np.where(vmask, assign, 0).astype(np.uint8)
        return IvfPqIndex(
            centroids=flat.centroids, codebooks=codebooks,
            grouped_codes=codes.reshape(nlist, c_pad, m),
            grouped_ids=flat.grouped_ids, grouped_valid=flat.grouped_valid,
            nlist=nlist, c_pad=c_pad, m=m, dsub=dsub)

    def device(self):
        return (jnp.asarray(self.centroids), jnp.asarray(self.codebooks),  # staging-ok: adopted by DeviceSegment.ann_staged
                jnp.asarray(self.grouped_codes),  # staging-ok: adopted by DeviceSegment.ann_staged
                jnp.asarray(self.grouped_ids),  # staging-ok: adopted by DeviceSegment.ann_staged
                jnp.asarray(self.grouped_valid))  # staging-ok: adopted by DeviceSegment.ann_staged


@partial(jax.jit, static_argnames=("k", "nprobe"))
def ivfpq_search_l2(centroids, codebooks, grouped_codes, grouped_ids,
                    grouped_valid, query, live, *, k: int, nprobe: int):
    """ADC (asymmetric distance) IVF-PQ search, l2 space.

    Per probe: residual query r = q - centroid; LUT[m, 256] =
    ||r_sub - codeword||^2 via one [m*256, dsub] matmul; per-vector
    distance = sum_m LUT[m, code_m] (table gather).  Returns opensearch
    l2 scores 1/(1+d2).
    """
    q = query.astype(jnp.float32)
    m, _, dsub = codebooks.shape
    c2 = jnp.sum(centroids * centroids, axis=1)
    cd = c2 - 2.0 * (centroids @ q)
    _, probes = lax.top_k(-cd, nprobe)                    # [P]

    def one_probe(ci):
        r = q - centroids[ci]                             # [d]
        rs = r.reshape(m, 1, dsub)                        # [m, 1, dsub]
        # LUT: squared distance from each sub-residual to each codeword
        diff = codebooks - rs                             # [m, 256, dsub]
        lut = jnp.sum(diff * diff, axis=-1)               # [m, 256]
        codes = grouped_codes[ci].astype(jnp.int32)       # [c_pad, m]
        # out[i, j] = lut[j, codes[i, j]] == lut.T[codes[i, j], j]
        d2 = jnp.sum(jnp.take_along_axis(
            lut.T, codes, axis=0), axis=1)                # [c_pad]
        ids = grouped_ids[ci]
        ok = (grouped_valid[ci]
              & live[jnp.clip(ids, 0, live.shape[0] - 1)] & (ids >= 0))
        return jnp.where(ok, 1.0 / (1.0 + d2), -jnp.inf), ids

    scores, ids = jax.vmap(one_probe)(probes)             # [P, c_pad]
    flat_s = scores.reshape(-1)
    flat_i = ids.reshape(-1)
    vals, idx = lax.top_k(flat_s, k)
    return vals, jnp.where(vals > -jnp.inf, flat_i[idx], -1)
