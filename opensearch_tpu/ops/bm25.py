"""BM25 scoring as batched XLA programs.

The reference's hot loop is doc-at-a-time WAND/MaxScore inside Lucene's
``Weight.bulkScorer`` (ref server/src/main/java/org/opensearch/search/
internal/ContextIndexSearcher.java:318).  On TPU the same work is a
data-parallel program over the whole segment:

    CSR gather of the query terms' postings  ->  BM25 per posting
    ->  scatter-add into a dense per-doc score vector  ->  lax.top_k

No pruning is needed: scoring *every* posting of the query terms is a
handful of fused HBM-bandwidth-bound ops, and ``top_k`` replaces the
priority queue.  This is the BM25S formulation (see PAPERS.md) with
query-time idf so scores stay consistent across segments (Lucene computes
collection-wide stats in IndexSearcher, not per segment).

All functions here are pure jnp and shape-static; the search executor
composes and ``jit``s them with bucketed shapes.
"""

from __future__ import annotations

import math

import opensearch_tpu.common.jaxenv  # noqa: F401

import jax.numpy as jnp
from jax import lax

K1_DEFAULT = 1.2
B_DEFAULT = 0.75


def idf(df: int, n_docs: int) -> float:
    """Lucene BM25Similarity idf: ln(1 + (N - df + 0.5) / (df + 0.5))."""
    return math.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))


def gather_postings(offsets, doc_ids, tfs, term_ids, term_active, *,
                    budget: int, pad_doc: int):
    """Flatten the postings of up to T terms into fixed-size arrays.

    The CSR rows selected by ``term_ids`` are laid end-to-end into a
    ``budget``-sized flat space via searchsorted over cumulative lengths —
    fully on-device, shape-static.

    Contract: the caller must choose ``budget >= sum(df[term_ids])``
    (the executor computes this from host-side df stats and rounds up to a
    power-of-two bucket); entries beyond ``budget`` would be silently
    dropped otherwise.

    Returns (docs[B], tfs[B], slot[B], valid[B]): ``slot`` is the index
    into ``term_ids`` that produced each entry.
    """
    starts = offsets[term_ids]
    lens = jnp.where(term_active, offsets[term_ids + 1] - starts, 0)
    cum = jnp.cumsum(lens)
    total = cum[-1]
    i = jnp.arange(budget, dtype=jnp.int32)
    slot = jnp.searchsorted(cum, i, side="right").astype(jnp.int32)
    slot = jnp.minimum(slot, term_ids.shape[0] - 1)
    prev = jnp.where(slot > 0, cum[slot - 1], 0)
    valid = i < total
    idx = jnp.where(valid, starts[slot] + i - prev, 0)
    d = jnp.where(valid, doc_ids[idx], pad_doc)
    tf = jnp.where(valid, tfs[idx], 0.0)
    return d, tf, slot, valid


def bm25_scores(offsets, doc_ids, tfs, doc_lens, term_ids, term_active,
                idfs, weights, avgdl, *, n_pad: int, budget: int,
                k1: float = K1_DEFAULT, b: float = B_DEFAULT):
    """Dense per-doc BM25 scores for a bag of weighted terms.

    ``idfs``/``weights`` are per query term (weights carry boosts and
    should-clause accumulation).  Returns float32 [n_pad]; score > 0 iff
    the doc matched at least one term.
    """
    d, tf, slot, valid = gather_postings(
        offsets, doc_ids, tfs, term_ids, term_active,
        budget=budget, pad_doc=n_pad - 1)
    dl = doc_lens[d]
    norm = k1 * (1.0 - b + b * dl / avgdl)
    contrib = idfs[slot] * weights[slot] * tf / (tf + norm)
    contrib = jnp.where(valid, contrib, 0.0)
    return jnp.zeros(n_pad, jnp.float32).at[d].add(contrib)


def bm25_score_count(offsets, doc_ids, tfs, doc_lens, term_ids, term_active,
                     idfs, weights, avgdl, *, n_pad: int, budget: int,
                     scored: bool, k1: float = K1_DEFAULT,
                     b: float = B_DEFAULT):
    """One gather, two scatters: dense per-doc BM25 scores AND per-doc count
    of matched query-term slots (for AND / minimum_should_match semantics).
    With ``scored=False`` the score scatter is skipped (filter context)."""
    d, tf, slot, valid = gather_postings(
        offsets, doc_ids, tfs, term_ids, term_active,
        budget=budget, pad_doc=n_pad - 1)
    count = jnp.zeros(n_pad, jnp.int32).at[d].add(valid.astype(jnp.int32))
    if not scored:
        return jnp.zeros(n_pad, jnp.float32), count
    dl = doc_lens[d]
    norm = k1 * (1.0 - b + b * dl / avgdl)
    contrib = idfs[slot] * weights[slot] * tf / (tf + norm)
    scores = jnp.zeros(n_pad, jnp.float32).at[d].add(
        jnp.where(valid, contrib, 0.0))
    return scores, count


def match_count(offsets, doc_ids, tfs, term_ids, term_active, *,
                n_pad: int, budget: int):
    """Per-doc count of DISTINCT matched query terms (for conjunctions and
    minimum_should_match).  tf >= 1 per posting entry, so counting entries
    per (term, doc) pair counts terms."""
    d, _tf, _slot, valid = gather_postings(
        offsets, doc_ids, tfs, term_ids, term_active,
        budget=budget, pad_doc=n_pad - 1)
    return jnp.zeros(n_pad, jnp.int32).at[d].add(valid.astype(jnp.int32))


def topk(scores, k: int):
    """Top-k by score; XLA's top_k breaks ties by lower index, which is
    exactly Lucene's ascending-doc-id tie-break."""
    return lax.top_k(scores, k)
