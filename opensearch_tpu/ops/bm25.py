"""BM25 scoring as batched XLA programs.

The reference's hot loop is doc-at-a-time WAND/MaxScore inside Lucene's
``Weight.bulkScorer`` (ref server/src/main/java/org/opensearch/search/
internal/ContextIndexSearcher.java:318).  On TPU the same work is a
data-parallel program over the whole segment:

    CSR gather of the query terms' postings  ->  BM25 per posting
    ->  scatter-add into a dense per-doc score vector  ->  lax.top_k

This is the BM25S formulation (see PAPERS.md): the tf-side factor
``tf / (tf + k1*(1-b + b*dl/avgdl))`` depends only on segment data plus
the shard-level ``avgdl``, so it is eagerly precomputed ONCE per
(field, avgdl) into a per-posting ``impacts`` column
(``compute_impacts``, staged by ``DeviceSegment.impacts``).  Query-time
scoring then degenerates to gather + weighted scatter-add — no per-query
norm arithmetic, no ``doc_lens`` gather.  Query-time global ``idf``
stays a multiplier so scores remain exactly consistent across segments
(Lucene computes collection-wide stats in IndexSearcher, not per
segment).

All functions here are pure jnp and shape-static; the search executor
composes and ``jit``s them with bucketed shapes.
"""

from __future__ import annotations

import math
from functools import partial

import opensearch_tpu.common.jaxenv  # noqa: F401

import jax
import jax.numpy as jnp
from jax import lax

K1_DEFAULT = 1.2
B_DEFAULT = 0.75

# Backend-specialized lowering for the scored term-bag hot path.
# XLA:CPU lowers scatter-add to a scalar loop (~50ns/update measured on
# avx512 hosts whose tuning carries prefer-no-scatter), which makes the
# per-posting score accumulation 10-25x slower than the same placement
# as a vectorized host fancy-index add.  On the CPU backend the term-bag
# top-k therefore runs host-side over the SAME precomputed impact table
# (Segment.impact_table — bit-identical to the staged device column);
# accelerator backends keep the XLA kernels.  None = decide from the
# active backend; tests force True/False to exercise either path.
HOST_SCORING = None
_HOST_AUTO = None


def host_scoring_enabled() -> bool:
    if HOST_SCORING is not None:
        return bool(HOST_SCORING)
    global _HOST_AUTO
    if _HOST_AUTO is None:
        _HOST_AUTO = jax.default_backend() == "cpu"
    return _HOST_AUTO


def idf(df: int, n_docs: int) -> float:
    """Lucene BM25Similarity idf: ln(1 + (N - df + 0.5) / (df + 0.5))."""
    return math.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))


@partial(jax.jit, static_argnames=("k1", "b"))
def compute_impacts(tfs, doc_ids, doc_lens, avgdl, *,
                    k1: float = K1_DEFAULT, b: float = B_DEFAULT):
    """Per-posting BM25 impact ``tf / (tf + k1*(1-b + b*dl/avgdl))``.

    Everything here is segment data except ``avgdl`` (shard-level, a
    traced scalar so a stats change never recompiles).  Padded posting
    slots carry tf=0 and decode to impact 0.  float32 end to end — the
    score-parity tests pin this expression bitwise, so keep the
    operation order in sync with the numpy reference in
    tests/test_impacts.py."""
    dl = doc_lens[doc_ids]
    norm = k1 * (1.0 - b + b * dl / avgdl)
    return tfs / (tfs + norm)


def gather_postings(offsets, doc_ids, tfs, term_ids, term_active, *,
                    budget: int, pad_doc: int):
    """Flatten the postings of up to T terms into fixed-size arrays.

    The CSR rows selected by ``term_ids`` are laid end-to-end into a
    ``budget``-sized flat space via searchsorted over cumulative lengths —
    fully on-device, shape-static.

    Contract: the caller must choose ``budget >= sum(df[term_ids])``
    (the executor computes this from host-side df stats and rounds up to a
    power-of-two bucket); entries beyond ``budget`` would be silently
    dropped otherwise.

    Returns (docs[B], tfs[B], slot[B], valid[B]): ``slot`` is the index
    into ``term_ids`` that produced each entry.
    """
    starts = offsets[term_ids]
    lens = jnp.where(term_active, offsets[term_ids + 1] - starts, 0)
    cum = jnp.cumsum(lens)
    total = cum[-1]
    i = jnp.arange(budget, dtype=jnp.int32)
    slot = jnp.searchsorted(cum, i, side="right").astype(jnp.int32)
    slot = jnp.minimum(slot, term_ids.shape[0] - 1)
    prev = jnp.where(slot > 0, cum[slot - 1], 0)
    valid = i < total
    idx = jnp.where(valid, starts[slot] + i - prev, 0)
    d = jnp.where(valid, doc_ids[idx], pad_doc)
    tf = jnp.where(valid, tfs[idx], 0.0)
    return d, tf, slot, valid


def gather_postings_packed(offsets, packed, base, term_ids, term_active,
                           *, width: int, budget: int, pad_doc: int):
    """``gather_postings`` over BIT-PACKED doc ids (index/codec.py):
    postings store ``doc - base[term]`` deltas at a fixed ``width`` bits,
    and each lane decodes its delta with two aligned uint32 reads — no
    prefix-sum chain, so random access (and therefore the shape-static
    CSR gather) is preserved.

    Returns (docs[B], idx[B], slot[B], valid[B]): ``idx`` is the flat
    posting index (for the quantized-impact gather) and ``slot`` the
    query-term slot, exactly like ``gather_postings``.
    """
    starts = offsets[term_ids]
    lens = jnp.where(term_active, offsets[term_ids + 1] - starts, 0)
    cum = jnp.cumsum(lens)
    total = cum[-1]
    i = jnp.arange(budget, dtype=jnp.int32)
    slot = jnp.searchsorted(cum, i, side="right").astype(jnp.int32)
    slot = jnp.minimum(slot, term_ids.shape[0] - 1)
    prev = jnp.where(slot > 0, cum[slot - 1], 0)
    valid = i < total
    idx = jnp.where(valid, starts[slot] + i - prev, 0)
    # bitpos = idx * width decomposed as idx = 32a + b so the word/bit
    # math never overflows int32 at 10M-doc posting counts
    a, b = idx >> 5, idx & 31
    bit = b * width
    w = a * width + (bit >> 5)
    off = (bit & 31).astype(jnp.uint32)
    pair = (packed[w].astype(jnp.uint64)
            | (packed[w + 1].astype(jnp.uint64) << jnp.uint64(32)))
    mask = jnp.uint64((1 << width) - 1)
    delta = ((pair >> off.astype(jnp.uint64)) & mask).astype(jnp.int32)
    tid = term_ids[slot]
    d = jnp.where(valid, base[tid] + delta, pad_doc)
    return d, idx, slot, valid


def bm25_scores(offsets, doc_ids, tfs, doc_lens, term_ids, term_active,
                idfs, weights, avgdl, *, n_pad: int, budget: int,
                k1: float = K1_DEFAULT, b: float = B_DEFAULT):
    """Dense per-doc BM25 scores for a bag of weighted terms.

    ``idfs``/``weights`` are per query term (weights carry boosts and
    should-clause accumulation).  Returns float32 [n_pad]; score > 0 iff
    the doc matched at least one term.
    """
    d, tf, slot, valid = gather_postings(
        offsets, doc_ids, tfs, term_ids, term_active,
        budget=budget, pad_doc=n_pad - 1)
    dl = doc_lens[d]
    norm = k1 * (1.0 - b + b * dl / avgdl)
    contrib = idfs[slot] * weights[slot] * tf / (tf + norm)
    contrib = jnp.where(valid, contrib, 0.0)
    return jnp.zeros(n_pad, jnp.float32).at[d].add(contrib)


def bm25_score_count(offsets, doc_ids, tfs, doc_lens, term_ids, term_active,
                     idfs, weights, avgdl, *, n_pad: int, budget: int,
                     scored: bool, k1: float = K1_DEFAULT,
                     b: float = B_DEFAULT):
    """One gather, two scatters: dense per-doc BM25 scores AND per-doc count
    of matched query-term slots (for AND / minimum_should_match semantics).
    With ``scored=False`` the score scatter is skipped (filter context)."""
    d, tf, slot, valid = gather_postings(
        offsets, doc_ids, tfs, term_ids, term_active,
        budget=budget, pad_doc=n_pad - 1)
    count = jnp.zeros(n_pad, jnp.int32).at[d].add(valid.astype(jnp.int32))
    if not scored:
        return jnp.zeros(n_pad, jnp.float32), count
    dl = doc_lens[d]
    norm = k1 * (1.0 - b + b * dl / avgdl)
    contrib = idfs[slot] * weights[slot] * tf / (tf + norm)
    scores = jnp.zeros(n_pad, jnp.float32).at[d].add(
        jnp.where(valid, contrib, 0.0))
    return scores, count


def impact_scores(offsets, doc_ids, impacts, term_ids, term_active,
                  idfs, weights, *, n_pad: int, budget: int):
    """Dense per-doc BM25 scores from PRECOMPUTED impacts: pure gather +
    weighted scatter-add, no norm recomputation.  ``impacts`` is the
    staged per-posting column (``compute_impacts``), indexed exactly
    like ``tfs``.  Fast path for required<=1 bags with positive
    weights: score > 0 iff the doc matched, so no count scatter runs."""
    d, imp, slot, valid = gather_postings(
        offsets, doc_ids, impacts, term_ids, term_active,
        budget=budget, pad_doc=n_pad - 1)
    base = idfs[slot] * imp
    contrib = jnp.where(valid, weights[slot] * base, 0.0)
    return jnp.zeros(n_pad, jnp.float32).at[d].add(contrib)


def impact_score_count(offsets, doc_ids, impacts, term_ids, term_active,
                       idfs, weights, *, n_pad: int, budget: int,
                       scored: bool):
    """Impact-path variant of ``bm25_score_count``: one gather, score
    scatter from precomputed impacts + matched-slot count scatter (AND /
    minimum_should_match semantics).  With ``scored=False`` only the
    count scatter runs (filter context)."""
    d, imp, slot, valid = gather_postings(
        offsets, doc_ids, impacts, term_ids, term_active,
        budget=budget, pad_doc=n_pad - 1)
    count = jnp.zeros(n_pad, jnp.int32).at[d].add(valid.astype(jnp.int32))
    if not scored:
        return jnp.zeros(n_pad, jnp.float32), count
    base = idfs[slot] * imp
    contrib = jnp.where(valid, weights[slot] * base, 0.0)
    scores = jnp.zeros(n_pad, jnp.float32).at[d].add(contrib)
    return scores, count


def match_count(offsets, doc_ids, tfs, term_ids, term_active, *,
                n_pad: int, budget: int):
    """Per-doc count of DISTINCT matched query terms (for conjunctions and
    minimum_should_match).  tf >= 1 per posting entry, so counting entries
    per (term, doc) pair counts terms."""
    d, _tf, _slot, valid = gather_postings(
        offsets, doc_ids, tfs, term_ids, term_active,
        budget=budget, pad_doc=n_pad - 1)
    return jnp.zeros(n_pad, jnp.int32).at[d].add(valid.astype(jnp.int32))


def topk(scores, k: int):
    """Top-k by score; XLA's top_k breaks ties by lower index, which is
    exactly Lucene's ascending-doc-id tie-break."""
    return lax.top_k(scores, k)
