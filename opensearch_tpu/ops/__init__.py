import opensearch_tpu.common.jaxenv  # noqa: F401  (x64 before any jax use)
