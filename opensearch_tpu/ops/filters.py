"""Filter masks over doc-value columns as scatter ops.

The reference evaluates filters doc-at-a-time through Lucene's
``ConstantScoreScorer``; here a filter is a dense boolean mask [n_pad]
computed in one vectorized pass over the column's expanded values
(``values``/``value_docs`` from the multi-valued CSR — see
index/segment.py).  Multi-valued semantics match SortedNumericDocValues:
a doc matches if ANY of its values matches.
"""

from __future__ import annotations

import opensearch_tpu.common.jaxenv  # noqa: F401

import jax.numpy as jnp


def _scatter_any(ok, value_docs, n_pad: int):
    return jnp.zeros(n_pad, bool).at[value_docs].max(ok)


def range_mask(values, value_docs, lo, hi, *, include_lo: bool,
               include_hi: bool, n_pad: int):
    """Docs with any value in the interval.  lo/hi may be -inf/+inf
    (pass dtype min/max for int columns)."""
    ok_lo = values >= lo if include_lo else values > lo
    ok_hi = values <= hi if include_hi else values < hi
    return _scatter_any(ok_lo & ok_hi, value_docs, n_pad)


def term_mask(values, value_docs, value, *, n_pad: int):
    """Docs with any value equal to ``value`` (term filter over a numeric
    or ordinal column)."""
    return _scatter_any(values == value, value_docs, n_pad)


def terms_mask(values, value_docs, query_values, *, n_pad: int):
    """Docs with any value in ``query_values`` [Q] (terms filter).
    O(V*Q) compare — fine for the typical small Q; large Q should go
    through sorted-membership instead."""
    ok = (values[:, None] == query_values[None, :]).any(axis=1)
    return _scatter_any(ok, value_docs, n_pad)


def postings_mask(offsets, doc_ids, tfs, term_ids, term_active, *,
                  n_pad: int, budget: int):
    """Docs containing any of the given indexed terms (term/terms filter
    over an indexed field without doc values)."""
    from opensearch_tpu.ops.bm25 import gather_postings

    d, _tf, _slot, valid = gather_postings(
        offsets, doc_ids, tfs, term_ids, term_active,
        budget=budget, pad_doc=n_pad - 1)
    return jnp.zeros(n_pad, bool).at[d].max(valid)
