"""k-NN distance kernels: brute-force exact search as batched matmuls.

The reference ecosystem's FAISS/nmslib C++ engines plug in via the k-NN
plugin SPI (ref server/src/main/java/org/opensearch/plugins/
SearchPlugin.java:151); on TPU the exact path IS the friendly one — a
[n_docs, dim] x [dim] (or [dim, q]) matmul feeds the MXU directly, and
``top_k`` replaces the heap.  Score translations match the opensearch-knn
plugin's space definitions so scores are drop-in comparable:

- l2:            1 / (1 + ||v - q||^2)
- cosinesimil:   (2 - (1 - cos)) / 2  == (1 + cos) / 2
- innerproduct:  d >= 0 ? d + 1 : 1 / (1 - d)
"""

from __future__ import annotations

from functools import partial

import opensearch_tpu.common.jaxenv  # noqa: F401
import jax
import jax.numpy as jnp
from jax import lax

SPACES = ("l2", "cosinesimil", "innerproduct")


@partial(jax.jit, static_argnames=("space",))
def knn_scores(vectors, valid, query, *, space: str):
    """Per-doc similarity scores [n_pad]; invalid rows score -inf.

    ``vectors`` [n_pad, d] float32, ``valid`` bool [n_pad] (exists & live),
    ``query`` [d].
    """
    q = query.astype(jnp.float32)
    dots = vectors @ q                                    # MXU
    if space == "l2":
        v2 = jnp.sum(vectors * vectors, axis=1)
        d2 = jnp.maximum(v2 - 2.0 * dots + jnp.dot(q, q), 0.0)
        scores = 1.0 / (1.0 + d2)
    elif space == "cosinesimil":
        norms = jnp.sqrt(jnp.sum(vectors * vectors, axis=1))
        qn = jnp.sqrt(jnp.dot(q, q))
        cos = dots / jnp.maximum(norms * qn, 1e-30)
        scores = (1.0 + cos) / 2.0
    elif space == "innerproduct":
        scores = jnp.where(dots >= 0, dots + 1.0, 1.0 / (1.0 - dots))
    else:
        raise ValueError(f"unknown space [{space}]")
    return jnp.where(valid, scores, -jnp.inf)


@partial(jax.jit, static_argnames=("space", "k"))
def knn_topk(vectors, valid, query, *, space: str, k: int):
    scores = knn_scores(vectors, valid, query, space=space)
    return lax.top_k(scores, k)


def knn_topk_auto(vectors, valid, query, *, space: str, k: int):
    """Exact top-k via the hand-written pallas kernel when opted in
    (OSTPU_PALLAS=1, see ops/pallas_knn.py) and the layout qualifies;
    the XLA-fused jnp path otherwise.  Identical results either way."""
    import os
    if os.environ.get("OSTPU_PALLAS") == "1":
        # pallas import deferred so the default path never loads it
        from opensearch_tpu.ops.pallas_knn import TILE, knn_scores_pallas
        if vectors.shape[0] % TILE == 0:
            # only real TPUs run the Mosaic-compiled kernel; everything
            # else (cpu tests, gpu) goes through the interpreter
            interpret = jax.default_backend() not in ("tpu", "axon")
            scores = knn_scores_pallas(vectors, valid, query, space=space,
                                       interpret=interpret)
            return lax.top_k(scores, k)
    return knn_topk(vectors, valid, query, space=space, k=k)


@partial(jax.jit, static_argnames=("space", "k"))
def knn_topk_batch(vectors, valid, queries, *, space: str, k: int):
    """Batched queries [Q, d] -> (scores [Q, k], ids [Q, k]).  One
    [n, d] x [d, Q] matmul for the whole batch — the throughput path."""
    q = queries.astype(jnp.float32)
    dots = vectors @ q.T                                  # [n, Q]
    if space == "l2":
        v2 = jnp.sum(vectors * vectors, axis=1)[:, None]
        q2 = jnp.sum(q * q, axis=1)[None, :]
        d2 = jnp.maximum(v2 - 2.0 * dots + q2, 0.0)
        scores = 1.0 / (1.0 + d2)
    elif space == "cosinesimil":
        norms = jnp.sqrt(jnp.sum(vectors * vectors, axis=1))[:, None]
        qn = jnp.sqrt(jnp.sum(q * q, axis=1))[None, :]
        cos = dots / jnp.maximum(norms * qn, 1e-30)
        scores = (1.0 + cos) / 2.0
    elif space == "innerproduct":
        scores = jnp.where(dots >= 0, dots + 1.0, 1.0 / (1.0 - dots))
    else:
        raise ValueError(f"unknown space [{space}]")
    scores = jnp.where(valid[:, None], scores, -jnp.inf)
    return lax.top_k(scores.T, k)
