"""Pallas TPU kernel for the exact k-NN scoring hot op.

The jnp formulation in ops/knn.py already lands on the MXU via XLA; this
kernel is the hand-scheduled variant per SURVEY §7's pallas mandate: the
vector matrix streams HBM -> VMEM one doc-tile at a time (grid over
tiles), each tile does one [T, d] @ [d] MXU matvec plus the VPU score
translation, writing its slice of the dense score vector — no
intermediate [n, d] temporaries, explicit control of the tile size.

Numerically identical to ``ops.knn.knn_scores`` (same formula, same
masking); validated against it in interpreter mode on CPU
(tests/test_pallas.py) and behind the ``OSTPU_PALLAS=1`` flag on real
TPUs.  Tile size 256 keeps a (256, d<=1024) f32 block well under VMEM.
"""

from __future__ import annotations

import functools

import opensearch_tpu.common.jaxenv  # noqa: F401
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE = 256


def _score_kernel_l2(v_ref, q_ref, valid_ref, out_ref):
    v = v_ref[...]                       # [TILE, d] f32 (VMEM)
    q = q_ref[...]                       # [1, d]
    dots = jnp.sum(v * q, axis=1)        # VPU reduce ([T] matvec)
    v2 = jnp.sum(v * v, axis=1)
    q2 = jnp.sum(q * q)
    d2 = jnp.maximum(v2 - 2.0 * dots + q2, 0.0)
    scores = 1.0 / (1.0 + d2)
    out_ref[...] = jnp.where(valid_ref[...], scores, -jnp.inf)


def _score_kernel_cosine(v_ref, q_ref, valid_ref, out_ref):
    v = v_ref[...]
    q = q_ref[...]
    dots = jnp.sum(v * q, axis=1)
    norms = jnp.sqrt(jnp.sum(v * v, axis=1))
    qn = jnp.sqrt(jnp.sum(q * q))
    cos = dots / jnp.maximum(norms * qn, 1e-30)
    out_ref[...] = jnp.where(valid_ref[...], (1.0 + cos) / 2.0, -jnp.inf)


def _score_kernel_ip(v_ref, q_ref, valid_ref, out_ref):
    v = v_ref[...]
    q = q_ref[...]
    dots = jnp.sum(v * q, axis=1)
    scores = jnp.where(dots >= 0, dots + 1.0, 1.0 / (1.0 - dots))
    out_ref[...] = jnp.where(valid_ref[...], scores, -jnp.inf)


_KERNELS = {"l2": _score_kernel_l2, "cosinesimil": _score_kernel_cosine,
            "innerproduct": _score_kernel_ip}


@functools.partial(jax.jit, static_argnames=("space", "interpret"))
def knn_scores_pallas(vectors, valid, query, *, space: str = "l2",
                      interpret: bool = False):
    """Drop-in pallas replacement for ``ops.knn.knn_scores``.

    ``vectors`` [n_pad, d] f32 with n_pad % TILE == 0 (the segment
    staging pads to pow2 >= 8, so any n_pad >= TILE qualifies; smaller
    inputs should use the jnp path).
    """
    kernel = _KERNELS.get(space)
    if kernel is None:
        raise ValueError(f"unknown space [{space}]")
    n_pad, d = vectors.shape
    assert n_pad % TILE == 0, n_pad
    grid = (n_pad // TILE,)
    q2d = query.astype(jnp.float32).reshape(1, d)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad,), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((TILE,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((TILE,), lambda i: (i,)),
        interpret=interpret,
    )(vectors.astype(jnp.float32), q2d, valid)
