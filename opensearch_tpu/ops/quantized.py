"""Quantized-impact scoring kernels (the device half of index/codec.py).

Same composition as the f32 impact kernels in ops/bm25.py — CSR gather,
weighted scatter-add, lax.top_k downstream — but the gather decodes
bit-packed doc-id deltas in-lane and the impact column dequantizes
int8/int16 codes against per-term scales, with an in-kernel override
for terms the exact-rank-parity guard stored as sparse f32
(``exact_vals``/``exact_offsets``).

Parity contract: every contribution is ``weights[slot] * (idfs[slot] *
imp)`` where ``imp = q.astype(f32) * scales[term]`` — float32, the
same multiply order as ``QuantizedPostings.dequantized()`` feeding
``TermBagPlan.host_topk``, so budget eviction and breaker-open
degradation stay byte-identical on quantized segments (the PR-5/11
invariant, extended to the compressed layout).

All functions are pure jnp and shape-static; ``width`` and ``budget``
are static so the executor's bucketed dims share XLA programs.
"""

from __future__ import annotations

import opensearch_tpu.common.jaxenv  # noqa: F401

import jax.numpy as jnp

from opensearch_tpu.ops.bm25 import gather_postings_packed


def _dequant(idx, slot, valid, offsets, term_ids, qvals, scales,
             exact_vals, exact_offsets):
    """Per-lane impact reconstruction: quantized code * per-term scale,
    overridden by the exact f32 block where the parity guard demanded
    one.  ``idx - starts`` is the in-list position, which indexes the
    exact CSR directly (same order as the postings CSR)."""
    tid = term_ids[slot]
    imp_q = qvals[idx].astype(jnp.float32) * scales[tid]
    pos = idx - offsets[term_ids][slot]
    e0 = exact_offsets[tid]
    has_exact = exact_offsets[tid + 1] > e0
    ei = jnp.clip(e0 + pos, 0, exact_vals.shape[0] - 1)
    imp = jnp.where(has_exact, exact_vals[ei], imp_q)
    return jnp.where(valid, imp, 0.0)


def quantized_impact_scores(offsets, packed, base, qvals, scales,
                            exact_vals, exact_offsets, term_ids,
                            term_active, idfs, weights, *, width: int,
                            n_pad: int, budget: int):
    """Quantized mirror of ``bm25.impact_scores`` (the required<=1
    positive-weight fast path: score > 0 iff matched, no count
    scatter).  The floor-of-1 quantization in index/codec.py is what
    keeps that equivalence: a matched posting never decodes to 0."""
    d, idx, slot, valid = gather_postings_packed(
        offsets, packed, base, term_ids, term_active,
        width=width, budget=budget, pad_doc=n_pad - 1)
    imp = _dequant(idx, slot, valid, offsets, term_ids, qvals, scales,
                   exact_vals, exact_offsets)
    base_score = idfs[slot] * imp
    contrib = jnp.where(valid, weights[slot] * base_score, 0.0)
    return jnp.zeros(n_pad, jnp.float32).at[d].add(contrib)


def quantized_impact_score_count(offsets, packed, base, qvals, scales,
                                 exact_vals, exact_offsets, term_ids,
                                 term_active, idfs, weights, *,
                                 width: int, n_pad: int, budget: int,
                                 scored: bool):
    """Quantized mirror of ``bm25.impact_score_count``: one gather,
    score scatter + matched-slot count scatter (AND /
    minimum_should_match semantics)."""
    d, idx, slot, valid = gather_postings_packed(
        offsets, packed, base, term_ids, term_active,
        width=width, budget=budget, pad_doc=n_pad - 1)
    count = jnp.zeros(n_pad, jnp.int32).at[d].add(valid.astype(jnp.int32))
    if not scored:
        return jnp.zeros(n_pad, jnp.float32), count
    imp = _dequant(idx, slot, valid, offsets, term_ids, qvals, scales,
                   exact_vals, exact_offsets)
    base_score = idfs[slot] * imp
    contrib = jnp.where(valid, weights[slot] * base_score, 0.0)
    scores = jnp.zeros(n_pad, jnp.float32).at[d].add(contrib)
    return scores, count
