"""Aggregation kernels: bucket counting and metrics as scatter-adds over
doc-value columns.

Analog of the reference's per-shard collect phase
(search/aggregations/BucketCollector.java:46 driving LeafBucketCollector
doc-at-a-time).  Here a bucket agg is one vectorized pass over a column's
expanded (value, owning-doc) arrays: bucket keys resolve via searchsorted
or direct ordinals, consecutive duplicate (doc, bucket) pairs are masked
out (docs count once per bucket, Lucene's sorted-values dedup), and counts
are a scatter-add.  Metric sub-aggs ride the same pass: per-doc partial
sums scatter into buckets through the bucket entries.
"""

from __future__ import annotations

import opensearch_tpu.common.jaxenv  # noqa: F401

import jax.numpy as jnp


def masked_centroids(values, value_docs, matched, *, n_cent: int):
    """Equal-weight centroids of the MATCHED values — the device side of
    the percentiles sketch (TDigest analog; ref
    search/aggregations/metrics TDigest percentiles).

    One device sort replaces host materialization of every matched value:
    invalid entries sort to +inf past the valid prefix, ranks bin the
    prefix into ``n_cent`` equal-count segments, and a segment-sum emits
    (means [n_cent] f64, weights [n_cent] i64) — the only host transfer
    is 2*n_cent numbers regardless of how many values matched.
    """
    ok = matched[value_docs]
    key = jnp.where(ok, values.astype(jnp.float64), jnp.inf)
    sv = jnp.sort(key)
    total = ok.sum()
    ranks = jnp.arange(sv.shape[0])
    valid = ranks < total
    bins = jnp.clip((ranks * n_cent) // jnp.maximum(total, 1), 0,
                    n_cent - 1).astype(jnp.int32)
    tgt = jnp.where(valid, bins, n_cent)
    sums = jnp.zeros(n_cent + 1, jnp.float64).at[tgt].add(
        jnp.where(valid, sv, 0.0))
    cnts = jnp.zeros(n_cent + 1, jnp.int64).at[tgt].add(
        valid.astype(jnp.int64))
    means = sums[:n_cent] / jnp.maximum(cnts[:n_cent], 1)
    return means, cnts[:n_cent]


def _first_occurrence(docs, buckets):
    """Mask of entries that are the first (doc, bucket) occurrence in the
    (sorted-per-doc) expanded arrays."""
    prev_same = jnp.concatenate([
        jnp.zeros(1, bool),
        (docs[1:] == docs[:-1]) & (buckets[1:] == buckets[:-1])])
    return ~prev_same


def ordinal_counts(ords, value_docs, matched, *, n_buckets_pad: int):
    """Per-ordinal doc counts over matched docs (terms agg on a keyword
    column; ordinals pre-deduped per doc at segment build)."""
    ok = matched[value_docs] & (ords >= 0)
    tgt = jnp.where(ok, ords, n_buckets_pad - 1)
    return jnp.zeros(n_buckets_pad, jnp.int64).at[tgt].add(
        ok.astype(jnp.int64))


def bucketed_counts(values, value_docs, matched, edges, *,
                    n_buckets_pad: int):
    """Histogram doc counts: bucket b covers [edges[b], edges[b+1]).
    ``edges`` must be ascending; values outside [edges[0], edges[-1]) are
    dropped.  Docs count once per bucket even with several values in it."""
    b = jnp.searchsorted(edges, values, side="right").astype(jnp.int32) - 1
    ok = (matched[value_docs] & (b >= 0) & (b < edges.shape[0] - 1))
    ok &= _first_occurrence(value_docs, b)
    tgt = jnp.where(ok, b, n_buckets_pad - 1)
    return jnp.zeros(n_buckets_pad, jnp.int64).at[tgt].add(
        ok.astype(jnp.int64))


def masked_metrics(values, value_docs, matched):
    """(sum, value_count, min, max) over every value of matched docs
    (SortedNumeric keeps duplicates — they all count)."""
    ok = matched[value_docs]
    fvals = values.astype(jnp.float64)
    s = jnp.where(ok, fvals, 0.0).sum()
    c = ok.sum()
    mn = jnp.where(ok, fvals, jnp.inf).min()
    mx = jnp.where(ok, fvals, -jnp.inf).max()
    return s, c, mn, mx


def per_doc_partials(values, value_docs, matched, *, n_pad: int):
    """Per-doc (sum, count, min, max) of a numeric column — the building
    block for metric sub-aggregations under bucket aggs."""
    ok = matched[value_docs]
    fvals = values.astype(jnp.float64)
    tgt = jnp.where(ok, value_docs, n_pad - 1)
    zero = jnp.zeros(n_pad, jnp.float64)
    s = zero.at[tgt].add(jnp.where(ok, fvals, 0.0))
    c = jnp.zeros(n_pad, jnp.int64).at[tgt].add(ok.astype(jnp.int64))
    mn = jnp.full(n_pad, jnp.inf).at[tgt].min(jnp.where(ok, fvals, jnp.inf))
    mx = jnp.full(n_pad, -jnp.inf).at[tgt].max(
        jnp.where(ok, fvals, -jnp.inf))
    return s, c, mn, mx


def scatter_partials_to_buckets(bucket_entries_docs, bucket_entries_b,
                                entry_ok, per_doc, *, n_buckets_pad: int):
    """Second-level scatter: per-doc metric partials -> per-bucket partials
    through the bucket-entry (doc, bucket) pairs (docs in several buckets
    contribute to each)."""
    s_doc, c_doc, mn_doc, mx_doc = per_doc
    tgt = jnp.where(entry_ok, bucket_entries_b, n_buckets_pad - 1)
    d = bucket_entries_docs
    s = jnp.zeros(n_buckets_pad, jnp.float64).at[tgt].add(
        jnp.where(entry_ok, s_doc[d], 0.0))
    c = jnp.zeros(n_buckets_pad, jnp.int64).at[tgt].add(
        jnp.where(entry_ok, c_doc[d], 0))
    mn = jnp.full(n_buckets_pad, jnp.inf).at[tgt].min(
        jnp.where(entry_ok, mn_doc[d], jnp.inf))
    mx = jnp.full(n_buckets_pad, -jnp.inf).at[tgt].max(
        jnp.where(entry_ok, mx_doc[d], -jnp.inf))
    return s, c, mn, mx
