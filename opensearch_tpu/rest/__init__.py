from opensearch_tpu.rest.controller import RestController  # noqa: F401
from opensearch_tpu.rest.http_server import HttpServer  # noqa: F401
